"""Schedule -> clock-tick lowering: the MPMD-to-SPMD compiler.

The reference executes pipeline schedules MPMD: each rank interprets ITS
instruction stream, synchronizing implicitly through blocking MPI Send/Recv
(pipe.py:330-466). Under jit/shard_map every device must run the SAME traced
program, so this module compiles the per-stage instruction streams into a
static *clock-tick program*: numpy tables, indexed [tick, stage], saying what
each stage computes, which mailbox slot it reads, whether it emits a payload,
and where arriving payloads are stored. The executor then runs one jitted
tick function under ``lax.scan``; ``jax.lax.ppermute`` moves payloads between
neighbor stages each tick (pipeline bubbles become masked no-op ticks —
exactly the blank cells of the reference's pebble graph, README.md:41).

The lowering is schedule-agnostic: any Schedule whose streams obey the
contract (one compute per step-group, sends attached to the producing
compute, recvs attached to the consuming compute) lowers automatically —
naive, GPipe, PipeDream-Flush and Inference all go through this one path.

Timing model (matches the executor's tick loop):
- a payload sent at tick t is delivered into the receiver's mailbox at the
  end of tick t and is consumable from tick t+1;
- each stage executes at most ONE compute item (forward or backward of one
  microbatch) per tick;
- a send always occurs in the same tick as the compute that produced it.

The simulator is also a verifier: it detects deadlocks, unmatched
sends/recvs, mailbox overflows and missing/duplicate microbatch work, so a
buggy schedule fails at lowering time with a readable error instead of
hanging a TPU collective.
"""

import dataclasses
from collections import deque

import numpy as np

from shallowspeed_tpu import schedules as S

# op codes in the tick tables. In a SPLIT program (backward_split) OP_BWD
# cells are the relay-critical B-input half — same tick the combined
# backward would occupy, same message structure — and OP_BWD_W cells are
# the deferred B-weight halves packed into former bubble ticks. In a
# RECOMPUTE program OP_FWD cells stash only the stage INPUT and
# OP_RECOMPUTE cells re-run the stage forward right before the backward,
# writing the residual stash the backward then consumes (torchgpipe trade:
# the stash lifetime shrinks from fwd->bwd to recompute->bwd).
OP_NOOP, OP_FWD, OP_BWD, OP_BWD_W, OP_RECOMPUTE = 0, 1, 2, 3, 4


@dataclasses.dataclass(frozen=True)
class WorkItem:
    """One compute event parsed from a device's instruction stream."""

    kind: int  # OP_FWD | OP_BWD | OP_BWD_W | OP_RECOMPUTE
    mubatch_id: int
    chunk: int = 0  # virtual-stage chunk on this device (0 unless interleaved)
    needs_fwd_msg: bool = False  # consumes activations from the prior stage
    needs_bwd_msg: bool = False  # consumes output-grad from the next stage
    sends_fwd: bool = False  # emits activations to the next stage
    sends_bwd: bool = False  # emits input-grad to the prior stage
    allreduce: bool = False  # this backward anchors the DP all-reduce


@dataclasses.dataclass(frozen=True)
class TickProgram:
    """Static SPMD program: everything the executor's scan body indexes.

    Tables are indexed [tick, device]. Without interleaving a device IS a
    stage (num_chunks == 1, ``chunk`` all zeros); with interleaving each
    device runs ``num_chunks`` virtual stages and ``chunk`` names the one
    active at each tick. ``load_in``/``is_head`` mark the ticks whose compute
    belongs to the global first/last model stage (replacing the
    device-position tests that stop working once stage identity varies per
    tick)."""

    num_ticks: int
    num_stages: int  # number of DEVICES on the pp axis
    num_micro_batches: int
    n_fwd_slots: int  # mailbox depths (trash slot = index n_slots)
    n_bwd_slots: int
    n_stash_slots: int  # activation-stash depth (trash = index n_stash_slots)
    is_training: bool
    op: np.ndarray  # (T, S) int32: OP_NOOP/FWD/BWD
    mb: np.ndarray  # (T, S) int32: microbatch id, trash = M
    read_fwd_slot: np.ndarray  # (T, S) int32: fwd-mail slot consumed, trash = K_f
    read_bwd_slot: np.ndarray  # (T, S) int32: bwd-mail slot consumed, trash = K_b
    in_fwd_slot: np.ndarray  # (T, S) int32: slot storing payload arriving from s-1
    in_bwd_slot: np.ndarray  # (T, S) int32: slot storing payload arriving from s+1
    send_fwd: np.ndarray  # (T, S) int32 0/1: emit fwd payload this tick
    send_bwd: np.ndarray  # (T, S) int32 0/1: emit bwd payload this tick
    stash_write: np.ndarray  # (T, S) int32: stash slot a forward fills (trash if none)
    stash_read: np.ndarray  # (T, S) int32: stash slot a backward consumes (trash)
    num_chunks: int = 1  # virtual stages per device (V)
    chunk: np.ndarray = None  # (T, S) int32: active virtual chunk (0 on noops)
    load_in: np.ndarray = None  # (T, S) int32 0/1: compute is global stage 0 fwd
    is_head: np.ndarray = None  # (T, S) int32 0/1: compute is the global last stage
    # split-backward extension (backward_split programs only): OP_BWD cells
    # are B-inputs, which PEEK the activation stash (masks/logits) without
    # freeing it and WRITE a grad-stash slot (the per-slot effective
    # output-grads); OP_BWD_W cells read+free both stashes. The activation
    # stash is therefore held from the forward to the B-WEIGHT tick, and
    # the grad stash from B-input to B-weight — both sized by the simulator
    # exactly like the activation stash, so the split schedule's extra
    # memory is a physical buffer shape, not prose.
    backward_split: bool = False
    n_gstash_slots: int = 0  # grad-stash depth (trash = index n_gstash_slots)
    stash_peek: np.ndarray = None  # (T, S) int32: stash slot a B-input consults
    gstash_write: np.ndarray = None  # (T, S) int32: grad-stash slot a B-input fills
    gstash_read: np.ndarray = None  # (T, S) int32: grad-stash slot a B-weight frees
    # activation-recompute extension (recompute programs only): OP_FWD cells
    # write the stage INPUT into an xin slot instead of residuals into the
    # activation stash; OP_RECOMPUTE cells read+free the xin slot, re-run
    # the forward and write the residual stash slot the backward consumes.
    # Global stage 0 skips the xin stash — its recompute reloads the
    # microbatch input directly (load_in marks those cells too).
    recompute: bool = False
    n_xin_slots: int = 0  # stage-input stash depth (trash = index n_xin_slots)
    xin_write: np.ndarray = None  # (T, S) int32: xin slot a forward fills
    xin_read: np.ndarray = None  # (T, S) int32: xin slot a recompute frees


class ScheduleLoweringError(ValueError):
    pass


def utilization(prog):
    """Active-cell fraction of a lowered program: computing (tick, device)
    cells / all cells. 1 - utilization is the bubble fraction of the pebble
    diagram (the blank cells of the reference's README.md:41 figure) — the
    schedule-quality number docs/lowering.md quotes (GPipe/1F1B 57% vs
    interleaved V=2 73% at P=4, M=4). Computed from the ACTUAL tick tables,
    so the documented bubble-shrink claims are testable artifacts, not prose.

    Note: cells are weighted equally. Across different ``num_chunks`` (V)
    an active cell is 1/(P·V) of the model, so equal per-cell WORK across
    compared layouts (same total model, same microbatches) is the caller's
    premise — true for the P-fixed comparisons the docs make. Equal
    weighting also cannot see the split-backward win (a combined backward
    cell is 2x a forward cell's FLOPs; splitting trades fewer heavy ticks
    for more uniform ones) — that is ``weighted_utilization``'s job.
    """
    active = int(np.sum(prog.op != OP_NOOP))
    return active / (prog.num_ticks * prog.num_stages)


def _op_weights(prog):
    """Per-op-code FLOP weights for this program, from the cost model's
    single source (``observability.costmodel.PIPELINE_OP_COSTS``): in a
    split program OP_BWD cells are B-inputs (dgrad only), in a combined
    program they are full backwards (dgrad + wgrad)."""
    from shallowspeed_tpu.observability.costmodel import PIPELINE_OP_COSTS as C

    bwd = C["bwd_in"] if prog.backward_split else C["bwd"]
    return np.array(
        [0.0, C["fwd"], bwd, C["bwd_w"], C["recompute"]], np.float64
    )


def weighted_makespan(prog):
    """FLOP-weighted makespan of the lowered program under the executor's
    lockstep tick model: every tick, each device runs its cell's op and the
    per-tick ``ppermute`` pair rejoins them, so a tick costs the MAXIMUM op
    weight across devices (a tick where one stage runs a combined backward
    while the rest forward costs a backward, not a forward). Weights come
    from ``costmodel.PIPELINE_OP_COSTS`` (fwd 1, combined bwd 2, split
    halves 1 each); the unit is one forward's work. All-noop ticks never
    occur in a lowered program (the greedy simulator always progresses), so
    their zero weight is unreachable."""
    w = _op_weights(prog)
    return float(w[np.asarray(prog.op)].max(axis=1).sum())


def weighted_utilization(prog):
    """FLOP-weighted active fraction: total cell work / (stages x weighted
    makespan). Unlike ``utilization`` this sees the split-backward win —
    splitting each 2-weight backward cell into two 1-weight halves shrinks
    the weighted makespan (backward-phase ticks stop costing double while
    the deferred halves fill former bubbles), so the weighted bubble
    fraction ``1 - weighted_utilization`` drops even where the equal-weight
    tick count grows. 1 - this is the number docs/lowering.md quotes for
    ``--backward-split``."""
    w = _op_weights(prog)
    span = weighted_makespan(prog)
    if span <= 0:
        return 1.0
    return float(w[np.asarray(prog.op)].sum() / (prog.num_stages * span))


def program_stats(prog, spec=None, mubatch_size=None, tp=1):
    """Static per-program telemetry: everything a metrics consumer needs to
    reason about a lowered schedule without replaying it — tick count, send
    volume, mailbox/stash footprints, per-device occupancy and the bubble
    fraction. Computed from the ACTUAL tick tables at lowering time (the
    executor's runtime per-tick behaviour is fully determined by them), so
    recording this once per program is the per-tick story with zero runtime
    cost. All values are plain Python scalars/lists — JSON-serializable as-is
    (the observability JSONL sink emits this dict verbatim).

    With ``spec`` + ``mubatch_size`` the dict additionally carries the
    PER-MODEL stash memory: ``stash_bytes_peak`` = slot count x slot
    activation bytes from the real spec's padded slot shapes (residual
    stash + the recompute xin stash + the split grad stash) — the number
    the report CLI's Memory section renders stashed-vs-recompute."""
    cells = prog.num_ticks * prog.num_stages
    util = utilization(prog)
    wutil = weighted_utilization(prog)
    # per-device occupancy: the fraction of ticks each pp device computes —
    # the per-row view of the pebble diagram (ramp devices idle longest)
    occupancy = [
        float(np.sum(prog.op[:, s] != OP_NOOP) / prog.num_ticks)
        for s in range(prog.num_stages)
    ]
    # per-op-kind cell counts: OP_BWD cells are B-inputs in a split
    # program, combined backwards otherwise (reported under the honest key)
    n_bwd = int(np.sum(prog.op == OP_BWD))
    stats = {
        "num_ticks": int(prog.num_ticks),
        "num_stages": int(prog.num_stages),
        "num_micro_batches": int(prog.num_micro_batches),
        "num_chunks": int(prog.num_chunks),
        "is_training": bool(prog.is_training),
        "backward_split": bool(prog.backward_split),
        "recompute": bool(prog.recompute),
        "active_cells": int(np.sum(prog.op != OP_NOOP)),
        "total_cells": int(cells),
        "cells_fwd": int(np.sum(prog.op == OP_FWD)),
        "cells_bwd": 0 if prog.backward_split else n_bwd,
        "cells_bwd_in": n_bwd if prog.backward_split else 0,
        "cells_bwd_w": int(np.sum(prog.op == OP_BWD_W)),
        "cells_recompute": int(np.sum(prog.op == OP_RECOMPUTE)),
        "sends_fwd": int(np.sum(prog.send_fwd)),
        "sends_bwd": int(np.sum(prog.send_bwd)),
        "fwd_mail_slots": int(prog.n_fwd_slots),
        "bwd_mail_slots": int(prog.n_bwd_slots),
        "stash_slots": int(prog.n_stash_slots),
        "grad_stash_slots": int(prog.n_gstash_slots),
        "xin_slots": int(prog.n_xin_slots),
        "stage_occupancy": occupancy,
        "utilization": float(util),
        "bubble_fraction": float(1.0 - util),
        "weighted_utilization": float(wutil),
        "weighted_bubble_fraction": float(1.0 - wutil),
    }
    if spec is not None and mubatch_size is not None:
        from shallowspeed_tpu.parallel.executor import stash_slot_nbytes

        per = stash_slot_nbytes(spec, mubatch_size, tp=tp)
        stats["stash_bytes_per_slot"] = int(per["stash"])
        stats["xin_bytes_per_slot"] = int(per["xin"])
        stats["gstash_bytes_per_slot"] = int(per["gstash"])
        stats["stash_bytes_peak"] = int(
            prog.n_stash_slots * per["stash"]
            + prog.n_xin_slots * per["xin"]
            + prog.n_gstash_slots * per["gstash"]
        )
    return stats


def program_flops(prog, spec, mubatch_size, tp=1):
    """Analytical PADDED FLOPs for ONE execution of this tick program on one
    pp(x tp)-group: the hardware-work leg of the observability cost model
    (observability/costmodel.py; the logical model-FLOP leg is
    ``mlp_train_flops_per_sample``).

    Every computing cell runs the SPMD executor's full padded slot stack —
    a forward is ``2 * mb * sum(o_l * i_l)`` over the PADDED per-slot dims
    (executor.slot_shapes), a backward twice that (dgrad + wgrad) —
    regardless of the stage's logical widths; that uniformity is exactly
    what makes the program SPMD, and exactly why padded FLOPs exceed
    logical FLOPs. Computed from the ACTUAL tick tables (counts of
    OP_FWD/OP_BWD cells), so the padding-tax number is an artifact of the
    real lowered program, not a formula that can drift from it. Multiply by
    ``dp`` for the whole mesh (each replica runs the program on its shard).

    ``tp``: the tensor-parallel degree — slot dims are tp-rounded, the
    GROUP total is returned (the Megatron shards partition every matmul,
    so each of the pp x tp devices executes exactly 1/(pp*tp) of it;
    divide accordingly for a per-device bound, as ``expected_comms`` does).
    """
    from shallowspeed_tpu.parallel.executor import slot_shapes

    padded_p = sum(o * i for o, i in slot_shapes(spec, tp))
    n_fwd = int(np.sum(prog.op == OP_FWD))
    n_bwd = int(np.sum(prog.op == OP_BWD))
    n_bwd_w = int(np.sum(prog.op == OP_BWD_W))
    # the recompute tax: every OP_RECOMPUTE cell re-runs a full stage
    # forward (2 units) — charged here so MFU and the cost-model
    # cross-check price recompute programs honestly
    n_rec = int(np.sum(prog.op == OP_RECOMPUTE))
    # split programs spread the backward's 4-unit work over an OP_BWD
    # (dgrad, 2) and an OP_BWD_W (wgrad, 2) cell: same total FLOPs
    bwd_unit = 2 if prog.backward_split else 4
    return (
        (2 * n_fwd + 2 * n_rec + bwd_unit * n_bwd + 2 * n_bwd_w)
        * mubatch_size
        * padded_p
    )


def program_comm_bytes(prog, spec, mubatch_size):
    """Analytical inter-stage traffic for ONE execution of this tick program
    — the pp-axis leg of the observability comms model
    (observability/program_audit.expected_comms).

    The executor relays with TWO uniform ``lax.ppermute``s (one per
    direction) EVERY tick, payload ``(mubatch_size, relay_width)`` f32 —
    masked no-op ticks ship zero payloads, but they are shipped (that
    uniformity is what makes the program SPMD), so the wire bytes each
    device moves per step are ``2 * num_ticks * payload``. The useful
    bytes (ticks whose send tables actually emit) ride alongside so the
    relay's own padding tax is a recorded number too. Computed from the
    ACTUAL tick tables, like ``program_stats``/``program_flops``.

    Returns plain scalars (JSON-able as-is): ``relay_payload_bytes`` (one
    direction, one tick), ``wire_bytes_per_device`` (2 x ticks x payload),
    ``useful_bytes_per_device`` (mean over devices of the send-table
    bytes), ``useful_sends`` (total send-table count), ``num_ticks``.

    This function covers the pp-axis relay only. The dp-axis gradient-sync
    leg — one anchor collective, or one collective PER BYTE-BUCKET when
    ``grad_bucket_bytes > 0`` — is modeled by
    ``parallel/gradsync.sync_comm_bytes`` (same per-bucket numbers the
    executor's emitters lower and the program audit verifies).
    """
    from shallowspeed_tpu.parallel.executor import relay_width

    payload = 4 * mubatch_size * relay_width(spec)
    useful_sends = int(np.sum(prog.send_fwd) + np.sum(prog.send_bwd))
    return {
        "relay_payload_bytes": int(payload),
        "num_ticks": int(prog.num_ticks),
        "wire_bytes_per_device": int(2 * prog.num_ticks * payload),
        "useful_sends": useful_sends,
        "useful_bytes_per_device": useful_sends * payload / prog.num_stages,
    }


def parse_stage_stream(commands, stage_id, num_stages, training=True, num_chunks=1):
    """Flatten one device's instruction stream into WorkItems + validate.

    Recv/Load instructions bind to the NEXT compute; Send instructions bind
    to the PREVIOUS compute — the same dataflow the reference Worker's buffer
    semantics imply (pipe.py:355-406: recv fills the buffer the next
    forward/backward reads; send ships the buffer the last compute wrote).

    Endpoint rules are in terms of the GLOBAL model stage ``chunk * P +
    device``: only stage 0 loads inputs / cannot receive activations or send
    input-grads; only stage S-1 loads targets / cannot receive output-grads
    or send activations. With num_chunks == 1 these reduce to the
    device-position rules.
    """
    last_stage_g = num_chunks * num_stages - 1

    def stage_g(chunk):
        return chunk * num_stages + stage_id

    items = []
    pend_fwd_msg = pend_bwd_msg = False
    seen_zero = seen_opt = False
    has_combined = has_split = False
    bin_keys, bww_keys = set(), set()  # (chunk, mubatch) with a B-in / B-w
    rec_keys = set()  # (chunk, mubatch) with a RecomputeForward
    for cmd in commands:
        if isinstance(cmd, S.ZeroGrad):
            if items or seen_zero:
                raise ScheduleLoweringError("ZeroGrad must be the first instruction")
            seen_zero = True
        elif isinstance(cmd, S.OptimizerStep):
            if seen_opt:
                raise ScheduleLoweringError("duplicate OptimizerStep")
            seen_opt = True
        elif isinstance(cmd, S.RecvActivations):
            if num_chunks == 1 and stage_id == 0:
                raise ScheduleLoweringError("stage 0 cannot RecvActivations")
            if pend_fwd_msg:
                raise ScheduleLoweringError("two RecvActivations before a Forward")
            pend_fwd_msg = True
        elif isinstance(cmd, S.RecvOutputGrad):
            if num_chunks == 1 and stage_id == num_stages - 1:
                raise ScheduleLoweringError("last stage cannot RecvOutputGrad")
            if pend_bwd_msg:
                raise ScheduleLoweringError("two RecvOutputGrads before a Backward")
            pend_bwd_msg = True
        elif isinstance(cmd, S.LoadMuBatchInput):
            if stage_id != 0:
                raise ScheduleLoweringError("only stage 0 loads inputs")
        elif isinstance(cmd, S.LoadMuBatchTarget):
            if stage_id != num_stages - 1:
                raise ScheduleLoweringError("only the last stage loads targets")
        elif isinstance(cmd, S.Forward):
            if seen_opt:
                raise ScheduleLoweringError("compute after OptimizerStep")
            if pend_bwd_msg:
                raise ScheduleLoweringError("RecvOutputGrad not consumed by a Backward")
            if pend_fwd_msg and stage_g(cmd.chunk_id) == 0:
                raise ScheduleLoweringError("global stage 0 cannot RecvActivations")
            items.append(
                WorkItem(
                    OP_FWD, cmd.mubatch_id, chunk=cmd.chunk_id,
                    needs_fwd_msg=pend_fwd_msg,
                )
            )
            pend_fwd_msg = False
        elif isinstance(cmd, S.RecomputeForward):
            # re-materializes residuals from the stashed stage input: no
            # messages in or out, like the deferred B-weight half
            if seen_opt:
                raise ScheduleLoweringError("compute after OptimizerStep")
            if pend_fwd_msg or pend_bwd_msg:
                raise ScheduleLoweringError(
                    "a Recv cannot bind to a RecomputeForward (it consumes "
                    "no messages — only the stashed stage input)"
                )
            key = (cmd.chunk_id, cmd.mubatch_id)
            if key in rec_keys:
                raise ScheduleLoweringError(
                    f"duplicate RecomputeForward for microbatch {cmd.mubatch_id}"
                )
            rec_keys.add(key)
            items.append(
                WorkItem(OP_RECOMPUTE, cmd.mubatch_id, chunk=cmd.chunk_id)
            )
        elif isinstance(cmd, (S.BackwardGradAcc, S.BackwardGradAllReduce)):
            if seen_opt:
                raise ScheduleLoweringError("compute after OptimizerStep")
            if pend_fwd_msg:
                raise ScheduleLoweringError("RecvActivations not consumed by a Forward")
            if pend_bwd_msg and stage_g(cmd.chunk_id) == last_stage_g:
                raise ScheduleLoweringError("global last stage cannot RecvOutputGrad")
            if rec_keys and (cmd.chunk_id, cmd.mubatch_id) not in rec_keys:
                raise ScheduleLoweringError(
                    f"Backward for microbatch {cmd.mubatch_id} precedes its "
                    "RecomputeForward (the backward consumes the residuals "
                    "the recompute re-materializes)"
                )
            has_combined = True
            items.append(
                WorkItem(
                    OP_BWD,
                    cmd.mubatch_id,
                    chunk=cmd.chunk_id,
                    needs_bwd_msg=pend_bwd_msg,
                    allreduce=isinstance(cmd, S.BackwardGradAllReduce),
                )
            )
            pend_bwd_msg = False
        elif isinstance(cmd, S.BackwardInputGradAcc):
            # the relay-critical half: same message structure as the
            # combined backward (consumes the output-grad, may send dx)
            if seen_opt:
                raise ScheduleLoweringError("compute after OptimizerStep")
            if pend_fwd_msg:
                raise ScheduleLoweringError("RecvActivations not consumed by a Forward")
            if pend_bwd_msg and stage_g(cmd.chunk_id) == last_stage_g:
                raise ScheduleLoweringError("global last stage cannot RecvOutputGrad")
            if rec_keys and (cmd.chunk_id, cmd.mubatch_id) not in rec_keys:
                raise ScheduleLoweringError(
                    f"BackwardInputGrad for microbatch {cmd.mubatch_id} "
                    "precedes its RecomputeForward (the B-input consults the "
                    "residuals the recompute re-materializes)"
                )
            has_split = True
            bin_keys.add((cmd.chunk_id, cmd.mubatch_id))
            items.append(
                WorkItem(
                    OP_BWD,
                    cmd.mubatch_id,
                    chunk=cmd.chunk_id,
                    needs_bwd_msg=pend_bwd_msg,
                )
            )
            pend_bwd_msg = False
        elif isinstance(cmd, S.BackwardWeightGradAcc):
            # the deferred half: no messages in or out — only the stashes
            if seen_opt:
                raise ScheduleLoweringError("compute after OptimizerStep")
            if pend_fwd_msg or pend_bwd_msg:
                raise ScheduleLoweringError(
                    "a Recv cannot bind to a BackwardWeightGrad (it consumes "
                    "no messages — only the activation and grad stashes)"
                )
            key = (cmd.chunk_id, cmd.mubatch_id)
            if key not in bin_keys:
                raise ScheduleLoweringError(
                    f"BackwardWeightGrad for microbatch {cmd.mubatch_id} "
                    "precedes its BackwardInputGrad (the weight half reads "
                    "the grad stash the input half fills)"
                )
            if key in bww_keys:
                raise ScheduleLoweringError(
                    f"duplicate BackwardWeightGrad for microbatch {cmd.mubatch_id}"
                )
            has_split = True
            bww_keys.add(key)
            items.append(
                WorkItem(
                    OP_BWD_W,
                    cmd.mubatch_id,
                    chunk=cmd.chunk_id,
                    allreduce=isinstance(cmd, S.BackwardWeightGradAllReduce),
                )
            )
        elif isinstance(cmd, S.SendActivations):
            if not items or items[-1].kind != OP_FWD or items[-1].sends_fwd:
                raise ScheduleLoweringError(
                    "SendActivations must directly follow its Forward"
                )
            if stage_g(items[-1].chunk) == last_stage_g:
                raise ScheduleLoweringError("global last stage cannot SendActivations")
            items[-1] = dataclasses.replace(items[-1], sends_fwd=True)
        elif isinstance(cmd, S.SendInputGrad):
            if not items or items[-1].kind != OP_BWD or items[-1].sends_bwd:
                raise ScheduleLoweringError(
                    "SendInputGrad must directly follow its Backward"
                )
            if stage_g(items[-1].chunk) == 0:
                raise ScheduleLoweringError("global stage 0 cannot SendInputGrad")
            items[-1] = dataclasses.replace(items[-1], sends_bwd=True)
        else:
            raise ScheduleLoweringError(f"unknown instruction {cmd!r}")
    if pend_fwd_msg or pend_bwd_msg:
        raise ScheduleLoweringError("dangling Recv with no consuming compute")
    if training and not (seen_zero and seen_opt):
        raise ScheduleLoweringError("training stream must bracket with ZeroGrad/OptimizerStep")
    if has_combined and has_split:
        raise ScheduleLoweringError(
            "stream mixes combined Backward and split BackwardInput/"
            "BackwardWeight instructions — a program is split or it is not"
        )
    for it in items:
        if not 0 <= it.chunk < num_chunks:
            raise ScheduleLoweringError(f"chunk {it.chunk} out of range [0,{num_chunks})")
    return items


class _Mailbox:
    """Receiver-side slot allocator for one direction at one device."""

    def __init__(self):
        self.free_from = []  # per slot: earliest tick this slot may take an arrival
        self.msgs = []  # FIFO of (sent_tick, slot, key)

    def deliver(self, tick, key):
        for i, f in enumerate(self.free_from):
            if f <= tick:
                self.free_from[i] = np.inf  # occupied
                self.msgs.append((tick, i, key))
                return i
        self.free_from.append(np.inf)
        self.msgs.append((tick, len(self.free_from) - 1, key))
        return len(self.free_from) - 1

    def _find(self, tick, key):
        for i, (sent, _, k) in enumerate(self.msgs):
            if sent < tick and k == key:
                return i
        return None

    def consumable(self, tick, key):
        """A delivered message for exactly this (chunk, microbatch) is
        available. Binding consumption by key (not FIFO position) both
        supports out-of-order consumers and turns sender/receiver order
        mismatches into visible deadlocks instead of silently mispairing
        activations."""
        return self._find(tick, key) is not None

    def consume(self, tick, key):
        i = self._find(tick, key)
        assert i is not None
        _, slot, _ = self.msgs.pop(i)
        self.free_from[slot] = tick  # reusable for arrivals this very tick
        return slot

    @property
    def depth(self):
        return len(self.free_from)


def lower_schedule(
    schedule_cls,
    num_micro_batches,
    num_stages,
    training=None,
    virtual=1,
    backward_split=False,
    recompute=False,
):
    """Compile a Schedule class into a TickProgram.

    ``num_stages`` is the number of pp DEVICES; ``virtual`` (V) is the number
    of virtual stages per device for interleaved schedules (the model has
    ``num_stages * virtual`` stages, stage ``s`` on device ``s % num_stages``
    as chunk ``s // num_stages``). V=1 is the ordinary one-stage-per-device
    case.

    ``backward_split``: lower the schedule's two-stage backward (B-input /
    B-weight) variant. B-inputs keep exactly the combined backward's ticks
    (same message structure, so the greedy simulation reproduces the same
    placement); B-weight items have no dependencies beyond their own
    B-input and are DEFERRED — each tick a stage first tries its next
    F/B-input item and, only when that is message-blocked or exhausted,
    runs its oldest pending B-weight instead, packing the weight halves
    into what were bubble ticks. FIFO deferral preserves the per-stage
    weight-grad accumulation order of the combined schedule (bit-identical
    fp sums); the verifier additionally rejects streams whose B-weight
    order disagrees with their B-input order, a B-weight without (or
    before) its B-input, and a DP anchor anywhere but the final B-weight.
    """
    if issubclass(schedule_cls, S.InterleavedSchedule):
        if backward_split:
            raise ScheduleLoweringError(
                "backward_split is not supported for interleaved schedules "
                "(the virtual-chunk steady state interleaves its own "
                "chunks; splitting its backward is future work)"
            )
        if recompute:
            raise ScheduleLoweringError(
                "recompute is not supported for interleaved schedules "
                "(per-chunk input stashes under the virtual-chunk steady "
                "state are future work)"
            )
        kw = {"num_chunks": virtual}  # V=1 degenerates to one chunk per device
    elif virtual != 1:
        raise ScheduleLoweringError(
            f"virtual={virtual} requires an interleaved schedule; "
            f"{schedule_cls.__name__} places one stage per device"
        )
    else:
        kw = {}
        if backward_split:
            kw["backward_split"] = True
        if recompute:
            kw["recompute"] = True
    streams = [
        S.flat_commands(
            schedule_cls(
                num_micro_batches=num_micro_batches,
                num_stages=num_stages,
                stage_id=s,
                **kw,
            )
        )
        for s in range(num_stages)
    ]
    if training is None:
        training = any(isinstance(c, S.OptimizerStep) for c in streams[0])
    stage_items = [
        parse_stage_stream(streams[s], s, num_stages, training, num_chunks=virtual)
        for s in range(num_stages)
    ]

    # a program is split iff any stage deferred weight grads — and then
    # every backward-bearing stage must be split the same way (each stage's
    # own stream already rejects intra-stream mixing)
    split = any(i.kind == OP_BWD_W for items in stage_items for i in items)
    if split:
        for s, items in enumerate(stage_items):
            if any(i.kind == OP_BWD for i in items) and not any(
                i.kind == OP_BWD_W for i in items
            ):
                raise ScheduleLoweringError(
                    f"stage {s}: combined backwards in a split program "
                    "(every stage must defer its weight grads or none may)"
                )

    # a program recomputes iff any stage emitted recompute cells — and then
    # every backward-bearing stage must recompute too (the executor's
    # forward branch stops stashing residuals program-wide)
    rec = any(i.kind == OP_RECOMPUTE for items in stage_items for i in items)
    if rec:
        for s, items in enumerate(stage_items):
            if any(i.kind == OP_BWD for i in items) and not any(
                i.kind == OP_RECOMPUTE for i in items
            ):
                raise ScheduleLoweringError(
                    f"stage {s}: backwards without RecomputeForwards in a "
                    "recompute program (every stage re-materializes its "
                    "residuals or none does)"
                )

    # validate per-device (chunk, microbatch) coverage
    want = sorted(
        (c, mb) for c in range(virtual) for mb in range(num_micro_batches)
    )
    for s, items in enumerate(stage_items):
        fwd = sorted((i.chunk, i.mubatch_id) for i in items if i.kind == OP_FWD)
        if fwd != want:
            raise ScheduleLoweringError(f"stage {s}: forwards {fwd} != chunks x 0..M-1")
        if training:
            bwd = sorted((i.chunk, i.mubatch_id) for i in items if i.kind == OP_BWD)
            if bwd != want:
                raise ScheduleLoweringError(f"stage {s}: backwards {bwd} != chunks x 0..M-1")
            if rec:
                rcs = sorted(
                    (i.chunk, i.mubatch_id)
                    for i in items
                    if i.kind == OP_RECOMPUTE
                )
                if rcs != want:
                    raise ScheduleLoweringError(
                        f"stage {s}: recomputes {rcs} != chunks x 0..M-1"
                    )
            if split:
                # exactly one B-weight per B-input, in the SAME per-stage
                # order: the weight-grad accumulators sum per microbatch in
                # B-weight order, so matching the B-input (= combined
                # backward) order is what keeps the fp sum — and therefore
                # the weight hash — bit-identical to the unsplit schedule
                bin_seq = [
                    (i.chunk, i.mubatch_id) for i in items if i.kind == OP_BWD
                ]
                bww_seq = [
                    (i.chunk, i.mubatch_id) for i in items if i.kind == OP_BWD_W
                ]
                if sorted(bww_seq) != want:
                    raise ScheduleLoweringError(
                        f"stage {s}: B-weights {sorted(bww_seq)} != chunks x 0..M-1"
                    )
                if bww_seq != bin_seq:
                    raise ScheduleLoweringError(
                        f"stage {s}: B-weight order {bww_seq} must match the "
                        f"B-input order {bin_seq} (weight-grad accumulation "
                        "order is the bitwise-parity contract)"
                    )
            ars = [i for i in items if i.allreduce]
            if split:
                bwws = [i for i in items if i.kind == OP_BWD_W]
                if len(ars) != 1 or bwws[-1] is not ars[0]:
                    raise ScheduleLoweringError(
                        f"stage {s}: the DP anchor must be exactly the final "
                        "B-weight (the gradient is incomplete until the last "
                        "deferred weight half lands)"
                    )
            else:
                bwds = [i for i in items if i.kind == OP_BWD]
                if len(ars) != 1 or bwds[-1] is not ars[0]:
                    raise ScheduleLoweringError(
                        f"stage {s}: BackwardGradAllReduce must be exactly the final backward"
                    )

    # --- greedy tick simulation -------------------------------------------
    # one compute per DEVICE per tick; messages keyed (chunk, microbatch).
    # Forward sends from device d chunk c go to the global next stage, which
    # is ALWAYS device (d+1) % P: chunk c for d < P-1, chunk c+1 on the ring
    # wrap d = P-1 -> 0. Backward mirrors it. That ring structure is why the
    # executor can use one uniform ppermute shift per direction.
    P = num_stages
    last_stage_g = virtual * P - 1
    ptr = [0] * P
    fwd_mail = [_Mailbox() for _ in range(P)]  # from the prior stage
    bwd_mail = [_Mailbox() for _ in range(P)]  # from the next stage
    # activation-stash allocation (training only): a forward claims a slot
    # for its residuals; the matching backward frees it (the B-WEIGHT in a
    # split program — the deferred wgrad still reads the activations, so
    # deferral extends the stash lifetime; the higher slot peak is the
    # split schedule's honest extra memory). Slot pressure is therefore the
    # schedule's REAL activation memory — GPipe peaks at M,
    # PipeDream-Flush at min(M, depth - stage): 1F1B's memory advantage
    # becomes physical buffer sizes, not just an instruction-stream property.
    stash_free_from = [[] for _ in range(P)]  # per device, per slot
    stash_of = [dict() for _ in range(P)]  # (chunk, mubatch) -> slot
    # grad-stash allocation (split programs): a B-input claims a slot for
    # the per-slot effective output-grads; the matching B-weight frees it.
    # Same discipline as the activation stash — held exactly from the
    # B-input tick to the B-weight tick, peak depth becomes buffer shapes.
    gstash_free_from = [[] for _ in range(P)]
    gstash_of = [dict() for _ in range(P)]
    # stage-input stash allocation (recompute programs): a forward claims a
    # slot for its INPUT (global stage 0 exempt — its recompute reloads the
    # microbatch from HBM); the matching recompute frees it and claims the
    # residual-stash slot instead. The residual stash is therefore held
    # only recompute->backward — the measurably lower peak the stash
    # analysis asserts.
    xin_free_from = [[] for _ in range(P)]
    xin_of = [dict() for _ in range(P)]
    # deferred B-weight items, FIFO per stage (FIFO = B-input order = the
    # combined schedule's accumulation order, the bitwise-parity contract)
    pending_w = [deque() for _ in range(P)]
    rows = []  # per tick: list of per-device dicts
    t = 0
    # recompute programs run one extra compute cell per (chunk, microbatch)
    limit = (5 if rec else 4) * virtual * num_micro_batches * P + 8 * virtual * P + 16
    while any(
        ptr[s] < len(stage_items[s]) or pending_w[s] for s in range(P)
    ):
        if t > limit:
            raise ScheduleLoweringError("schedule failed to converge (livelock?)")
        row = [
            dict(
                op=OP_NOOP, mb=num_micro_batches, rf=-1, rb=-1, sf=0, sb=0,
                inf=-1, inb=-1, sw=-1, sr=-1, ck=0, li=0, ih=0,
                sp=-1, gw=-1, gr=-1, xw=-1, xr=-1,
            )
            for _ in range(P)
        ]
        arrivals = []  # (direction, to_device, key)
        progressed = False
        for s in range(P):
            items = stage_items[s]
            # defer B-weights as the pointer reaches them: no message
            # dependencies, so they wait for an idle tick instead of
            # delaying the relay-critical stream behind them
            while ptr[s] < len(items) and items[ptr[s]].kind == OP_BWD_W:
                pending_w[s].append(items[ptr[s]])
                ptr[s] += 1
            item = items[ptr[s]] if ptr[s] < len(items) else None
            blocked = item is None or (
                item.needs_fwd_msg
                and not fwd_mail[s].consumable(t, (item.chunk, item.mubatch_id))
            ) or (
                item.needs_bwd_msg
                and not bwd_mail[s].consumable(t, (item.chunk, item.mubatch_id))
            )
            if blocked:
                if not pending_w[s]:
                    continue  # a true bubble tick
                # pack the oldest deferred B-weight into this bubble
                w = pending_w[s].popleft()
                key = (w.chunk, w.mubatch_id)
                r = row[s]
                r["op"], r["mb"], r["ck"] = OP_BWD_W, w.mubatch_id, w.chunk
                slot = stash_of[s].pop(key)
                stash_free_from[s][slot] = t + 1  # activations done
                r["sr"] = slot
                gslot = gstash_of[s].pop(key)
                gstash_free_from[s][gslot] = t + 1
                r["gr"] = gslot
                progressed = True
                continue
            if (
                item.kind == OP_RECOMPUTE
                and pending_w[s]
                and stash_free_from[s]
                and all(f > t for f in stash_free_from[s])
            ):
                # Drain a deferred B-weight BEFORE starting the next
                # microbatch's recompute when every residual-stash slot is
                # occupied: the B-weight frees its slot, so the recompute
                # about to claim one reuses it instead of growing the peak.
                # Without this rule a split-backward drain phase holds all M
                # stashes (every tick has r/B work, so B-weights never pack
                # into bubbles) and recompute buys no peak reduction. FIFO
                # order is preserved — same accumulation order as the
                # stashed twin, so bitwise parity holds; the cost is
                # delaying the relay stream by one tick per drained
                # B-weight, the memory-for-time recompute trade.
                w = pending_w[s].popleft()
                wkey = (w.chunk, w.mubatch_id)
                r = row[s]
                r["op"], r["mb"], r["ck"] = OP_BWD_W, w.mubatch_id, w.chunk
                slot = stash_of[s].pop(wkey)
                stash_free_from[s][slot] = t + 1
                r["sr"] = slot
                gslot = gstash_of[s].pop(wkey)
                gstash_free_from[s][gslot] = t + 1
                r["gr"] = gslot
                progressed = True
                continue
            key = (item.chunk, item.mubatch_id)
            # execute item at tick t
            stage_g = item.chunk * P + s
            r = row[s]
            r["op"], r["mb"], r["ck"] = item.kind, item.mubatch_id, item.chunk
            r["li"] = int(
                stage_g == 0 and item.kind in (OP_FWD, OP_RECOMPUTE)
            )
            r["ih"] = int(stage_g == last_stage_g)
            if item.needs_fwd_msg:
                r["rf"] = fwd_mail[s].consume(t, key)
            if item.needs_bwd_msg:
                r["rb"] = bwd_mail[s].consume(t, key)
            if training and item.kind == OP_FWD:
                if rec:
                    # stash the stage INPUT only; residuals wait for the
                    # recompute (global stage 0 reloads from HBM instead)
                    if stage_g != 0:
                        xfree = xin_free_from[s]
                        for xslot, f in enumerate(xfree):
                            if f <= t:
                                break
                        else:
                            xfree.append(0)
                            xslot = len(xfree) - 1
                        xfree[xslot] = np.inf  # held until the recompute
                        xin_of[s][key] = xslot
                        r["xw"] = xslot
                else:
                    free = stash_free_from[s]
                    for slot, f in enumerate(free):
                        if f <= t:
                            break
                    else:
                        free.append(0)
                        slot = len(free) - 1
                    free[slot] = np.inf  # occupied until the matching backward
                    stash_of[s][key] = slot
                    r["sw"] = slot
            elif training and item.kind == OP_RECOMPUTE:
                # free the input stash and claim the residual-stash slot the
                # imminent backward consumes — the short stash lifetime
                if stage_g != 0:
                    xslot = xin_of[s].pop(key)
                    xin_free_from[s][xslot] = t + 1
                    r["xr"] = xslot
                free = stash_free_from[s]
                for slot, f in enumerate(free):
                    if f <= t:
                        break
                else:
                    free.append(0)
                    slot = len(free) - 1
                free[slot] = np.inf  # occupied until the matching backward
                stash_of[s][key] = slot
                r["sw"] = slot
            elif training and item.kind == OP_BWD:
                if split:
                    # B-input: PEEK the activation stash (masks + logits;
                    # the B-weight frees it) and claim a grad-stash slot
                    r["sp"] = stash_of[s][key]
                    gfree = gstash_free_from[s]
                    for gslot, f in enumerate(gfree):
                        if f <= t:
                            break
                    else:
                        gfree.append(0)
                        gslot = len(gfree) - 1
                    gfree[gslot] = np.inf  # held until the matching B-weight
                    gstash_of[s][key] = gslot
                    r["gw"] = gslot
                else:
                    slot = stash_of[s].pop(key)
                    stash_free_from[s][slot] = t + 1  # reusable next tick
                    r["sr"] = slot
            if item.sends_fwd:
                r["sf"] = 1
                dst = (s + 1) % P
                dst_chunk = item.chunk + (1 if s == P - 1 else 0)
                arrivals.append(("fwd", dst, (dst_chunk, item.mubatch_id)))
            if item.sends_bwd:
                r["sb"] = 1
                dst = (s - 1) % P
                dst_chunk = item.chunk - (1 if s == 0 else 0)
                arrivals.append(("bwd", dst, (dst_chunk, item.mubatch_id)))
            ptr[s] += 1
            progressed = True
        if not progressed:
            state = [(s, ptr[s], len(stage_items[s])) for s in range(P)]
            raise ScheduleLoweringError(f"deadlock at tick {t}: {state}")
        for direction, dst, key in arrivals:
            mail = fwd_mail[dst] if direction == "fwd" else bwd_mail[dst]
            slot = mail.deliver(t, key)
            row[dst]["inf" if direction == "fwd" else "inb"] = slot
        rows.append(row)
        t += 1

    for s in range(num_stages):
        if fwd_mail[s].msgs or bwd_mail[s].msgs:
            raise ScheduleLoweringError(f"stage {s}: unconsumed messages at end")

    for s in range(num_stages):
        if stash_of[s]:
            raise ScheduleLoweringError(f"stage {s}: unfreed activation stash")
        if gstash_of[s]:
            raise ScheduleLoweringError(f"stage {s}: unfreed grad stash")
        if xin_of[s]:
            raise ScheduleLoweringError(f"stage {s}: unfreed input stash")

    K_f = max((m.depth for m in fwd_mail), default=0) or 1
    K_b = max((m.depth for m in bwd_mail), default=0) or 1
    K_s = max((len(f) for f in stash_free_from), default=0) or 1
    K_g = max((len(f) for f in gstash_free_from), default=0) if split else 0
    K_x = max((len(f) for f in xin_free_from), default=0) if rec else 0
    T = len(rows)

    def table(key, trash):
        out = np.full((T, num_stages), 0, dtype=np.int32)
        for ti, row in enumerate(rows):
            for s in range(num_stages):
                v = row[s][key]
                out[ti, s] = trash if v == -1 else v
        return out

    def raw(key):
        return np.array(
            [[r[s][key] for s in range(num_stages)] for r in rows], np.int32
        )

    return TickProgram(
        num_ticks=T,
        num_stages=num_stages,
        num_micro_batches=num_micro_batches,
        n_fwd_slots=K_f,
        n_bwd_slots=K_b,
        n_stash_slots=K_s,
        is_training=training,
        op=raw("op"),
        mb=raw("mb"),
        read_fwd_slot=table("rf", K_f),
        read_bwd_slot=table("rb", K_b),
        in_fwd_slot=table("inf", K_f),
        in_bwd_slot=table("inb", K_b),
        send_fwd=raw("sf"),
        send_bwd=raw("sb"),
        stash_write=table("sw", K_s),
        stash_read=table("sr", K_s),
        num_chunks=virtual,
        chunk=raw("ck"),
        load_in=raw("li"),
        is_head=raw("ih"),
        backward_split=split,
        n_gstash_slots=K_g,
        stash_peek=table("sp", K_s),
        gstash_write=table("gw", K_g),
        gstash_read=table("gr", K_g),
        recompute=rec,
        n_xin_slots=K_x,
        xin_write=table("xw", K_x),
        xin_read=table("xr", K_x),
    )
