"""Latency-denominated load bench: p50/p99, goodput and the saturation knee.

    python -m shallowspeed_tpu.serving.bench_serving [--dp N] [--pp M]
        [--schedule gpipe] [--rates 50,100,200,400] [--requests 100]
        [--slo-ms 50] [--seed 0] [--out BENCH_SERVING.json]

``bench_scaling`` scores the framework in samples/s; this bench opens the
second scoreboard the ROADMAP's "millions of users" north star asks for —
tail latency under load. For each offered rate it drives ``--requests``
seeded Poisson arrivals through a ``ServingEngine`` in open-loop mode
(arrivals independent of completions, enqueue backdated to scheduled
arrival — queueing delay lands in latency, never silently throttles the
offered load) and records p50/p99 latency, goodput (SLO-met completions per
second), achieved rate, queue depth and padding waste. The saturation knee
is the first rate whose tail violates the SLO or whose achieved rate falls
measurably below the offered one — the operating ceiling every future speed
PR is measured against.

Output is ONE versioned JSON document (``bench_version`` + per-row fields,
beside ``bench_scaling``'s records): the analytical latency floor
(``costmodel.serving_latency_bound`` — inference ticks x per-tick cost) is
recorded next to the measured percentiles so the gap between model and tail
is a number, not prose.

NOTE on interpretation (the honest caveat every CPU bench row in this repo
carries): on emulated CPU devices dispatch overhead dominates the tiny MLP,
so absolute latencies validate the machinery; the SHAPE of the sweep (flat
-> knee -> queue blow-up) is the transferable result.
"""

import argparse
import json
import sys

from shallowspeed_tpu.serving.engine import ServingEngine
from shallowspeed_tpu.serving.loadgen import (
    poisson_arrivals,
    request_payloads,
    run_open_loop,
)

BENCH_VERSION = 1
SWEEP_ROW_FIELDS = (
    "offered_rps",
    "completed",
    "dropped",
    "p50_latency_s",
    "p99_latency_s",
    "goodput_rps",
    "achieved_rps",
    "queue_depth_max",
    "queue_depth_mean",
    "padding_waste",
    "dispatches",
)


def find_knee(rows, slo_ms, achieved_fraction=0.9):
    """The saturation knee: the first offered rate (rows are swept in
    ascending offered order) whose p99 exceeds the SLO or whose achieved
    rate falls below ``achieved_fraction`` x offered. None = no knee
    inside the swept range (the verdict then says so instead of guessing)."""
    for row in rows:
        p99 = row.get("p99_latency_s")
        if slo_ms is not None and p99 is not None and p99 > slo_ms / 1000.0:
            return row["offered_rps"]
        ach, off = row.get("achieved_rps"), row.get("offered_rps")
        if ach is not None and off and ach < achieved_fraction * off:
            return row["offered_rps"]
    return None


def sweep(
    session,
    rates,
    n_requests=100,
    seed=0,
    slo_ms=None,
    rows_choices=(1, 2, 3, 4, 8),
    metrics=None,
):
    """Run the offered-load sweep on an existing session; returns the
    versioned JSON-able bench record. The SAME seeded request stream is
    replayed at every rate (only the arrival clock changes), so rows
    differ by load, not workload."""
    engine = ServingEngine(session, slo_ms=slo_ms, metrics=metrics)
    # compile every rung before the sweep: the percentiles must measure
    # serving under load, not the first rate's XLA compiles
    engine.warm_ladder()
    payloads = request_payloads(
        n_requests, session.spec.sizes[0], seed=seed, rows_choices=rows_choices
    )
    rows = []
    for rate in sorted(rates):
        engine.reset_stats()
        arrivals = poisson_arrivals(rate, n_requests, seed=seed)
        run_open_loop(engine, payloads, arrivals)
        rec = engine.record_summary(offered_rps=rate)
        rows.append({k: rec.get(k) for k in SWEEP_ROW_FIELDS})
    bound = session.inference_latency_bound()
    return {
        "bench": "serving",
        "bench_version": BENCH_VERSION,
        "config": {
            "dp": session.dp,
            "pp": session.pp,
            "schedule": session.schedule,
            "slot_rows": session.slot_rows,
            "slot_ladder": list(session.slot_ladder),
            "requests_per_rate": n_requests,
            "seed": seed,
            "slo_ms": slo_ms,
            "rows_choices": list(rows_choices),
        },
        "latency_bound_s": bound["seconds"],
        "latency_bound_ticks": bound["ticks"],
        "latency_bound_source": bound["peak_source"],
        "sweep": rows,
        "knee_rps": find_knee(rows, slo_ms),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m shallowspeed_tpu.serving.bench_serving",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--pp", type=int, default=1)
    ap.add_argument(
        "--schedule",
        choices=["naive", "gpipe", "pipedream", "interleaved"],
        default="gpipe",
    )
    ap.add_argument("--global-batch-size", type=int, default=128)
    ap.add_argument("--mubatches", type=int, default=4)
    ap.add_argument("--data-dir", default=None)
    ap.add_argument(
        "--checkpoint", default=None, help="serve these weights (PR6 loader)"
    )
    ap.add_argument(
        "--rates",
        default="50,100,200,400",
        help="comma-separated offered loads (requests/second)",
    )
    ap.add_argument("--requests", type=int, default=100, help="requests per rate")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--slo-ms", type=float, default=None)
    ap.add_argument(
        "--rows",
        default="1,2,3,4,8",
        help="comma-separated request row-count choices",
    )
    ap.add_argument("--out", default=None, help="write the JSON record here")
    args = ap.parse_args(argv)

    from shallowspeed_tpu.api import TrainingSession

    session = TrainingSession(
        dp=args.dp,
        pp=args.pp,
        schedule=args.schedule,
        global_batch_size=args.global_batch_size,
        mubatches=args.mubatches,
        data_dir=args.data_dir,
        resume=args.checkpoint,
    )
    record = sweep(
        session,
        rates=[float(r) for r in args.rates.split(",") if r.strip()],
        n_requests=args.requests,
        seed=args.seed,
        slo_ms=args.slo_ms,
        rows_choices=tuple(int(r) for r in args.rows.split(",") if r.strip()),
    )
    text = json.dumps(record, indent=2)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text + "\n")
        print(f"bench_serving record written: {args.out}")
        knee = record["knee_rps"]
        print(
            "saturation knee: "
            + (f"{knee} rps" if knee is not None else "not reached in sweep")
        )
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
