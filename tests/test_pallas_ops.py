"""Pallas kernel tests (interpreter mode on CPU, real kernels on TPU).

Verifies the fused linear+relu forward/backward kernels against the XLA path
and that the whole model trains identically with the Pallas backend enabled.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shallowspeed_tpu import model as Mo
from shallowspeed_tpu import ops, pallas_ops, trainer
from shallowspeed_tpu.optimizer import SGD

RNG = np.random.RandomState(0)


def r(*shape):
    return jnp.asarray(RNG.randn(*shape).astype(np.float32))


class TestKernels:
    def test_fwd_matches_xla(self):
        x, w, b = r(16, 24), r(20, 24), r(1, 20)
        y, mask = pallas_ops.linear_relu_fwd(x, w, b)
        y_ref = ops.relu(ops.linear(x, w, b))
        mask_ref = ops.linear(x, w, b) > 0
        np.testing.assert_allclose(y, y_ref, rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(np.asarray(mask) > 0, np.asarray(mask_ref))

    def test_bwd_matches_xla(self):
        x, w = r(16, 24), r(20, 24)
        g = r(16, 20)
        mask = (r(16, 20) > 0).astype(jnp.float32)
        dx, dw, db = pallas_ops.linear_relu_bwd(g, mask, x, w)
        dx_r, dw_r, db_r = ops.linear_grad(g * mask, x, w)
        np.testing.assert_allclose(dx, dx_r, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(dw, dw_r, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(db).reshape(-1), db_r, rtol=1e-5, atol=1e-6)

    def test_bwd_matches_autograd(self):
        x, w, b = r(8, 12), r(10, 12), r(1, 10)

        def f_ref(x, w, b):
            return (ops.relu(ops.linear(x, w, b)) ** 2).sum()

        y, mask = pallas_ops.linear_relu_fwd(x, w, b)
        g = 2 * y
        dx, dw, db = pallas_ops.linear_relu_bwd(g, mask, x, w)
        gx, gw, gb = jax.grad(f_ref, argnums=(0, 1, 2))(x, w, b)
        np.testing.assert_allclose(dx, gx, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(dw, gw, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(db, gb, rtol=1e-4, atol=1e-5)


class TestTiledKernels:
    """Grid-tiled variants on ragged shapes: multi-tile grids in every
    dimension plus edge padding, checked against the XLA path."""

    MB, DIN, DOUT, TILE = 300, 260, 200, 128  # 3x3x2 tiles, all ragged

    def test_tiled_fwd_matches_xla(self):
        x, w, b = r(self.MB, self.DIN), r(self.DOUT, self.DIN), r(1, self.DOUT)
        y, mask = pallas_ops.linear_relu_fwd_tiled(x, w, b, tile=self.TILE)
        z = np.asarray(ops.linear(x, w, b))
        # contraction order differs between the tiled kernel and XLA, so z
        # values within float noise of 0 may legitimately flip relu sides
        np.testing.assert_allclose(y, np.maximum(z, 0), rtol=1e-5, atol=1e-4)
        stable = np.abs(z) > 1e-4
        np.testing.assert_array_equal(
            (np.asarray(mask) > 0)[stable], (z > 0)[stable]
        )

    def test_tiled_bwd_matches_xla(self):
        x, w = r(self.MB, self.DIN), r(self.DOUT, self.DIN)
        g = r(self.MB, self.DOUT)
        mask = (r(self.MB, self.DOUT) > 0).astype(jnp.float32)
        dx, dw, db = pallas_ops.linear_relu_bwd_tiled(g, mask, x, w, tile=self.TILE)
        dx_r, dw_r, db_r = ops.linear_grad(g * mask, x, w)
        np.testing.assert_allclose(dx, dx_r, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(dw, dw_r, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(db).reshape(-1), db_r, rtol=1e-4, atol=1e-4
        )

    def test_dispatch_picks_tiled_beyond_budget(self, monkeypatch):
        fits = pallas_ops._fwd_bytes(128, 784, 128) <= pallas_ops.SINGLE_BLOCK_BUDGET_BYTES
        assert fits  # flagship layers stay single-block
        assert pallas_ops._fwd_bytes(4096, 8192, 4096) > pallas_ops.SINGLE_BLOCK_BUDGET_BYTES
        assert pallas_ops._bwd_bytes(4096, 8192, 4096) > pallas_ops.SINGLE_BLOCK_BUDGET_BYTES

        # run the PUBLIC entry points down the tiled branch: budget forced to
        # 0 and unique shapes so jit can't serve a cached single-block trace
        monkeypatch.setattr(pallas_ops, "SINGLE_BLOCK_BUDGET_BYTES", 0)
        monkeypatch.setattr(pallas_ops, "TILE", 128)
        mb, din, dout = 37, 29, 23
        x, w, b = r(mb, din), r(dout, din), r(1, dout)
        y, mask = pallas_ops.linear_relu_fwd(x, w, b)
        z = np.asarray(ops.linear(x, w, b))
        np.testing.assert_allclose(y, np.maximum(z, 0), rtol=1e-5, atol=1e-4)
        g = r(mb, dout)
        dx, dw, db = pallas_ops.linear_relu_bwd(g, mask, x, w)
        dx_r, dw_r, db_r = ops.linear_grad(
            g * jnp.asarray(mask), x, w
        )
        np.testing.assert_allclose(dx, dx_r, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(dw, dw_r, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(db).reshape(-1), db_r, rtol=1e-4, atol=1e-4
        )


class TestModelIntegration:
    def test_training_identical_with_pallas_backend(self):
        SIZES, B, M = (20, 16, 12, 10), 32, 4
        rng = np.random.RandomState(1)
        X = rng.randn(3, M, B // M, SIZES[0]).astype(np.float32)
        Y = np.eye(SIZES[-1], dtype=np.float32)[
            rng.randint(0, SIZES[-1], (3, M, B // M))
        ]
        results = []
        for use_pallas in (False, True):
            ops.set_pallas(use_pallas)
            try:
                spec = Mo.make_model_spec(SIZES, 1, B)
                params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
                step = trainer.make_train_step(spec, SGD(0.01))
                st = ()
                for i in range(3):
                    params, st = step(params, st, jnp.asarray(X[i]), jnp.asarray(Y[i]))
                results.append([l for s in params for l in s])
            finally:
                ops.set_pallas(False)
        for a, b in zip(*results):
            np.testing.assert_allclose(
                np.asarray(a["W"]), np.asarray(b["W"]), rtol=1e-5, atol=1e-7
            )
