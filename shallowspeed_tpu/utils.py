"""Utilities: layout-independent model hashing + replica-sync verification.

Capability parity with /root/reference/shallowspeed/utils.py (rank-0 print,
SHA1-of-SHA1s model hash, cross-replica sync assert), strengthened for the
mesh world: the hash is computed over the *logical* per-layer (W, b) blocks in
global layer order, so a sequential run, a DP=4 run and a DP=2xPP=4 run of the
same model produce the SAME hash — the reference could only compare hashes
within one layout (utils.py:13-31).
"""

from hashlib import sha1

import jax
import numpy as np


def model_hash(params_list) -> str:
    """SHA1 over concatenated per-parameter SHA1s, in global layer order.

    ``params_list``: list (per stage) of lists of {"W","b"} arrays (jax or
    numpy). Mirrors reference utils.py:13-24 (sha1 of each param's bytes,
    concatenated, re-hashed).
    """
    acc = ""
    for stage in params_list:
        for layer in stage:
            for key in ("W", "b"):
                arr = np.ascontiguousarray(jax.device_get(layer[key]), np.float32)
                acc += sha1(arr.tobytes()).hexdigest()
    return sha1(acc.encode("utf-8")).hexdigest()


def assert_dp_replicas_in_sync(arr) -> None:
    """Verify every data-parallel replica holds bit-identical parameters.

    The reference gathers per-process hashes over the dp communicator and
    compares (utils.py:27-31, train.py:154-155). Here replication is a
    *sharding invariant* of the params jax.Array (replicated over the ``dp``
    mesh axis); we verify it physically by hashing every addressable shard
    per device-row and comparing. Works on any pytree of arrays.
    """
    mismatches = []

    def check(x):
        if not isinstance(x, jax.Array):
            return
        by_index = {}
        for shard in x.addressable_shards:
            h = sha1(np.ascontiguousarray(shard.data).tobytes()).hexdigest()
            prev = by_index.setdefault(shard.index, h)
            if prev != h:
                mismatches.append((shard.device, shard.index))

    jax.tree.map(check, arr)
    if mismatches:
        raise ValueError(f"replica desync detected at shards: {mismatches}")


def p0print(*args, **kwargs):
    """Print from process 0 only (reference rprint, utils.py:8-10)."""
    if jax.process_index() == 0:
        print(*args, **kwargs)
