"""Checkpoint / resume: layout-independent on-disk snapshots.

The reference has NO checkpointing in its framework (SURVEY §5.4 — only its
PyTorch baseline script saves weights for divergence comparison). Here it is
a first-class subsystem, designed around the same principle as init and
hashing: checkpoints store the *logical* per-layer (W, b) blocks in global
layer order, so a model trained DP=2 x PP=4 can be saved and resumed
sequentially, or vice versa — the layout is a property of the run, not of
the checkpoint.

Format: a single .npz (atomic rename on save) with arrays ``w{i}``/``b{i}``
per global layer plus a JSON metadata blob (sizes, global batch size, epoch,
optimizer state).
"""

import json
import os
import tempfile
from pathlib import Path

import numpy as np

from shallowspeed_tpu.model import ModelSpec, make_model_spec

FORMAT_VERSION = 1


def _flatten_logical(params_list):
    """Per-stage ragged params -> flat global layer list (host numpy)."""
    import jax

    out = []
    for stage in params_list:
        for layer in stage:
            out.append(
                (
                    np.asarray(jax.device_get(layer["W"]), np.float32),
                    np.asarray(jax.device_get(layer["b"]), np.float32).reshape(1, -1),
                )
            )
    return out


def save_checkpoint(path, params_list, spec: ModelSpec, epoch: int, extra=None):
    """Atomically write params (+ metadata) to ``path`` (.npz)."""
    path = Path(path)
    flat = _flatten_logical(params_list)
    if len(flat) != len(spec.sizes) - 1:
        raise ValueError(
            f"param count {len(flat)} does not match spec sizes {spec.sizes}"
        )
    meta = {
        "format_version": FORMAT_VERSION,
        "sizes": list(spec.sizes),
        "global_batch_size": spec.global_batch_size,
        "epoch": int(epoch),
        "extra": extra or {},
    }
    arrays = {"meta": np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8)}
    for i, (w, b) in enumerate(flat):
        arrays[f"w{i}"] = w
        arrays[f"b{i}"] = b
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=path.parent, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_checkpoint(path, n_stages: int, global_batch_size=None):
    """Load a checkpoint and re-partition it for an ``n_stages`` layout.

    ``global_batch_size``: the CURRENT run's global batch size — it feeds the
    loss-scaling spec, so resurrecting the saved value when the run uses a
    different batch size would silently mis-scale every gradient. Defaults to
    the saved value for same-configuration resumes.

    Returns (params_list, spec, meta): params_list is per-stage ragged host
    numpy ready for ``jax.tree.map(jnp.asarray, ...)`` (sequential) or
    ``executor.stack_params`` (pipeline).
    """
    with np.load(Path(path)) as z:
        meta = json.loads(bytes(z["meta"]).decode())
        if meta.get("format_version") != FORMAT_VERSION:
            raise ValueError(f"unsupported checkpoint version: {meta}")
        n_layers = len(meta["sizes"]) - 1
        flat = [(z[f"w{i}"], z[f"b{i}"]) for i in range(n_layers)]
    if global_batch_size is None:
        global_batch_size = meta["global_batch_size"]
    spec = make_model_spec(meta["sizes"], n_stages, global_batch_size)
    params_list, k = [], 0
    for sspec in spec.stages:
        layers = []
        for _ in range(sspec.n_linears):
            w, b = flat[k]
            layers.append({"W": w, "b": b})
            k += 1
        params_list.append(layers)
    # shape sanity against the re-partitioned spec
    for sspec, layers in zip(spec.stages, params_list):
        for l, layer in enumerate(layers):
            want = (sspec.local_sizes[l + 1], sspec.local_sizes[l])
            if layer["W"].shape != want:
                raise ValueError(
                    f"checkpoint layer shape {layer['W'].shape} != spec {want}"
                )
    return params_list, spec, meta
