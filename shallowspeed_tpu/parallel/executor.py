"""SPMD pipeline executor: tick programs over a (dp, pp) mesh via shard_map.

This is the TPU-native replacement for the reference's Worker runtime
(/root/reference/shallowspeed/pipe.py:330-466). Where the Worker interprets
instructions against NumPy buffers and blocking MPI calls, here the whole
batch — every pipeline tick of every stage, the DP gradient reduction and the
optimizer step — is ONE jitted XLA computation:

- stages live on the ``pp`` mesh axis; each device holds its stage's
  parameters as one row of zero-padded stacked arrays, so the deliberately-
  unequal stages (2/2/2/1 Linears at PP=4, SURVEY §7.3) run under a single
  SPMD program. Padding is PER LAYER SLOT, not global: slot l is stacked to
  ``(S, max_out_l, max_in_l)`` — for the flagship model that is (S,128,784)
  and (S,127,128) instead of (S,2,784,784), an ~10x cut in padded FLOPs;
- the per-batch instruction streams are pre-compiled by ``lowering`` into a
  static tick table; the executor ``lax.scan``s one tick function whose body
  ``lax.switch``es between {noop, forward, backward} — pipeline bubbles are
  the noop branch (masked compute, like the blank cells of the pebble graph);
- stage-to-stage activation/grad relays are ``jax.lax.ppermute`` shifts over
  ``pp`` (the reference's blocking Send/Recv pairs, pipe.py:367-381);
- microbatch activation stashes (reference Module._cache) are fixed-shape
  ring buffers carried through the scan; mailbox slots come from the lowering;
- split-backward programs (``backward_split`` schedules, 2BP arxiv
  2405.18047) add a FOURTH switch branch: OP_BWD cells run only the
  relay-critical dgrad chain (B-input, stashing the per-slot effective
  output-grads into a grad-stash ring), and OP_BWD_W cells — packed by the
  lowering into former bubble ticks — finish the deferred wgrads from the
  activation + grad stashes, accumulating in the combined schedule's order
  so the fp sums (and the weight hash) are bit-identical;
- the dp axis is a four-point memory lattice (``zero`` in {0, 1, 2, 3} —
  arXiv 2004.13336's stages over this executor's stacked layout). Stage 0
  (plain DP): one ``jax.lax.psum`` of the whole accumulated gradient
  pytree over ``dp`` at the tail anchor, every replica repeats the full
  update. Stage 1 (ZeRO-1): the tail reduce-scatters the FLAT gradient,
  each replica updates its 1/dp chunk with its optimizer-state shard, and
  one deferred all-gather rebuilds the params. Stage 2 (ZeRO-2): the tail
  reduce-scatters PER LAYER SLOT straight from the accumulator slabs into
  the block-cyclic shard layout below — the flat gradient concat never
  materializes, the post-sync gradient lives only as this rank's shard,
  and per-slot all-gathers rebuild the updated params. Stage 3 (ZeRO-3):
  params REST in the block-cyclic shard and every tick branch all-gathers
  just the active chunk's slots on demand (gathered copies die with the
  branch), while the backward reduce-scatters each tick's slot gradients
  immediately — peak live params is one stage chunk, not the model.
  ``grad_bucket_bytes`` composes at stages 0-2: byte-bucketed collectives
  (parallel/gradsync.py) split the anchor sync into backward-ordered
  buckets, one collective each, so XLA's latency-hiding scheduler can
  overlap bucket k's communication with the consumers of already-synced
  buckets — the reference's per-parameter Iallreduce engine
  (pipe.py:302-327) with the bucketing its docstring wishes for. Stages
  0-2 are bitwise identical to each other modulo norm-scalar
  reassociation (elementwise collectives; see the ZeRO sections below);
  stage 3's per-tick sync reassociates the microbatch/replica sum order
  and carries the standard cross-layout tolerance instead;
- the optimizer step happens on-device on the padded params (padded regions
  receive exactly-zero gradients, so they stay zero — see tests);
- on a mesh with a ``tp`` axis (parallel/mesh.py, ``--tp``), every slot's
  W is additionally Megatron-sharded across the tp ranks — even slots
  column-parallel, odd slots row-parallel, one ``psum`` over ``tp`` per
  row slot forward and per column slot backward (2 all-reduces per layer
  pair per pass; see the tp stage functions below). Slot dims round up to
  tp multiples (``slot_shapes(spec, tp)``), per-device weight memory /
  optimizer state / matmul FLOPs divide by tp, and tp composes with DP,
  ZeRO-1, grad bucketing, the split backward and every schedule. At
  ``tp == 1`` none of this code is traced: the historical 2-axis programs
  are byte-identical.

Zero-padding invariant: weights are zero outside each layer's logical
(out_dim, in_dim) block, activations are zero beyond each boundary's true
width, the softmax head masks invalid columns to probability zero, and
targets are zero-padded — so every gradient is exactly zero outside its
logical block and padded compute is numerically inert, not approximately so.
Width changes between slots use ``_fit`` (slice-or-pad), which is exact
because stacked-slot widths always cover the true content (validated at
stack time).
"""

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from shallowspeed_tpu import ops
from shallowspeed_tpu.model import ModelSpec, init_model
from shallowspeed_tpu.parallel.compat import shard_map
from shallowspeed_tpu.parallel.lowering import (
    OP_BWD,
    OP_BWD_W,
    OP_FWD,
    OP_RECOMPUTE,
    TickProgram,
)
from shallowspeed_tpu.parallel.mesh import mesh_tp


# ---------------------------------------------------------------------------
# Per-slot stacked parameters
# ---------------------------------------------------------------------------


def slot_shapes(spec: ModelSpec, tp: int = 1):
    """Static per-slot stacked dims: [(out_l, in_l)] with maxima over stages.

    Also validates the passthrough-width invariant: any stage that is shorter
    than the deepest stage must have an out_dim that fits through every
    later slot's widths (true for the reference's monotone size lists).

    ``tp > 1`` (tensor parallelism): each dim is rounded up to a multiple
    of ``tp`` so every slot splits evenly across the tp ranks — the same
    zero-padding invariant that already makes unequal stages exact makes
    the extra columns exact. TP additionally requires the CHAINED width
    equality ``in_{l} == out_{l-1}`` at every row-parallel (odd) slot: a
    column slot hands its successor a rank-SHARD, and a shard of a
    narrower fit is not the fit of a shard, so unequal chained widths
    cannot be repaired locally. Monotone-decreasing size lists (the
    reference family, and everything the fuzz generates) satisfy it.
    """
    L = max((s.n_linears for s in spec.stages), default=0) or 1
    dims = []
    for l in range(L):
        outs = [s.local_sizes[l + 1] for s in spec.stages if s.n_linears > l]
        ins = [s.local_sizes[l] for s in spec.stages if s.n_linears > l]
        dims.append((max(outs), max(ins)))
    for s in spec.stages:
        for l in range(s.n_linears, L):
            o, i = dims[l]
            if s.out_dim > min(o, i):
                raise ValueError(
                    f"stage with out_dim={s.out_dim} cannot pass through slot {l} "
                    f"of width {min(o, i)}; use equal-depth stages for this size list"
                )
    if tp > 1:
        for l in range(1, L, 2):  # row-parallel slots consume a rank shard
            if dims[l][1] != dims[l - 1][0]:
                raise ValueError(
                    f"tp={tp} needs chained slot widths (in_{l} == out_{l - 1}) "
                    f"but slot {l} consumes {dims[l][1]} from a slot producing "
                    f"{dims[l - 1][0]}; use a monotone-decreasing size list"
                )
        dims = [(-(-o // tp) * tp, -(-i // tp) * tp) for o, i in dims]
    return dims


def tp_local_dims(dims, tp: int):
    """Per-device slot geometry under ``tp``-way Megatron sharding, derived
    from the (already tp-rounded) global stacked dims. Returns
    ``(w_dims, b_widths, xs_widths, mask_widths)``:

    - ``w_dims[l]``: this rank's W block — even (COLUMN-parallel) slots
      hold an ``(out/tp, in)`` row band, odd (ROW-parallel) slots an
      ``(out, in/tp)`` column band;
    - ``b_widths[l]``: every bias is sharded ``out/tp`` (row-parallel
      biases are rank-scattered and summed by the slot's psum, so no
      parameter is ever tp-replicated — the grad-norm reduction over
      ('pp','tp') counts each element exactly once);
    - ``xs_widths[l]`` / ``mask_widths[l]``: the stashed residuals in the
      representation the backward consumes — a column slot stashes its
      FULL input and its SHARDED pre-activation mask, a row slot the
      sharded input and the full post-psum mask.

    At ``tp == 1`` every formula collapses to the unsharded dims, so the
    tp=1 trace is byte-identical to the historical one.
    """
    w_dims = [
        (o // tp, i) if l % 2 == 0 else (o, i // tp)
        for l, (o, i) in enumerate(dims)
    ]
    b_widths = [o // tp for o, _ in dims]
    xs_widths = [i if l % 2 == 0 else i // tp for l, (_, i) in enumerate(dims)]
    mask_widths = [o // tp if l % 2 == 0 else o for l, (o, _) in enumerate(dims)]
    return w_dims, b_widths, xs_widths, mask_widths


def tp_allreduce_sites(spec: ModelSpec, tp: int, training: bool = True):
    """The Megatron all-reduce sites of ONE stage pass at this tp degree:
    ``(fwd_widths, bwd_widths)`` — payload widths (f32 columns of one
    ``(mubatch, width)`` psum over 'tp') in execution order. Forward: one
    psum per row-parallel (odd) slot, plus the closing reassembly when the
    last slot is column-parallel (the stage boundary must relay the FULL
    activation); backward (training only): one psum per column-parallel
    (even) slot — the Megatron f-operator. For an even slot count this is
    exactly 2 all-reduces per column/row layer pair per fwd+bwd pass.

    This is the ONE site list: the executor's tp stage functions place
    their psums by the same slot parity, and ``expected_comms`` sizes the
    tp axis of the census contract from these widths — so the audited
    contract and the traced program can never disagree about where the
    tp collectives sit or how big they are.
    """
    dims = slot_shapes(spec, tp)
    L = len(dims)
    fwd = [dims[l][0] for l in range(1, L, 2)]
    if (L - 1) % 2 == 0:
        fwd.append(dims[-1][0])
    bwd = [dims[l][1] for l in range(0, L, 2)] if training else []
    return fwd, bwd


def stash_slot_nbytes(spec: ModelSpec, mubatch_size: int, tp: int = 1):
    """Per-slot byte cost of each stash ring the executor carries, from the
    real spec's padded slot shapes — the ONE sizing the observability layer
    (``program_stats(spec=...)``, the report CLI's Memory section) uses to
    turn lowering slot counts into HBM bytes. Returns a dict:

    - ``"stash"``: one residual-stash slot — the per-slot activations
      (``xs_widths``, f32), the backward multipliers (``mask_widths``;
      1-byte bools for the relu family, f32 gelu-derivative values for the
      gelu family) and the head-logit stash row (``D_out``, f32);
    - ``"xin"``: one recompute input-stash slot (the stage input, f32);
    - ``"gstash"``: one split grad-stash slot (per-slot effective
      output-grads — f32 at the mask widths, because g_eff lives in the
      same representation as its mask).
    """
    dims = slot_shapes(spec, tp)
    _, _, xs_widths, mask_widths = tp_local_dims(dims, tp)
    mask_bytes = 1 if spec.act == "relu" else 4
    mb = mubatch_size
    return {
        "stash": 4 * mb * sum(xs_widths)
        + mask_bytes * mb * sum(mask_widths)
        + 4 * mb * dims[-1][0],
        "xin": 4 * mb * dims[0][1],
        "gstash": 4 * mb * sum(mask_widths),
    }


def relay_width(spec: ModelSpec) -> int:
    """True maximum inter-stage boundary width: the widest activation (and
    therefore activation-gradient) ever shipped over the ``pp`` axis.

    Stage ``s`` sends its out_dim forward (= stage ``s+1``'s in_dim) and its
    in_dim backward, so both relay directions are bounded by
    ``max(in_dim of stages 1..S-1)``. For the flagship model at PP=4 that is
    127 (stage in_dims 127/125/123) —
    ~6x narrower than sizing payloads to the model input width (784), which
    is what the reference's per-boundary buffers get for free
    (pipe.py:446-454) and the padded SPMD program must compute explicitly.
    """
    return max((s.in_dim for s in spec.stages[1:]), default=1)


def interleave_order(n_stages: int, n_devices: int):
    """Device-major stacked-row order for interleaved layouts: stacked row
    ``r = device * V + chunk`` holds model stage ``chunk * P + device``, so a
    plain P('pp') shard of the stage axis gives device ``d`` exactly its V
    virtual chunks, contiguously."""
    assert n_stages % n_devices == 0
    V = n_stages // n_devices
    return [(r % V) * n_devices + (r // V) for r in range(n_stages)]


def stack_params(params_list, spec: ModelSpec, order=None, tp: int = 1):
    """Per-stage ragged params -> per-slot zero-padded stacks + flags.

    Returns (stacked, flags):
      stacked = {"W": tuple_l of (S, out_l, in_l), "b": tuple_l of (S, out_l)}
      flags   = {"active": (S,L), "relu": (S,L), "residual": (S,L),
                 "head_mask": (S, out_last)}

    ``relu[r, l]`` is the stage's per-slot ACTIVATION flag (the key predates
    the model zoo): apply the spec's activation family (relu or gelu) after
    slot l. ``residual[r, l]`` marks the gelu family's residual adds
    (y_l += x_{l-1}); always all-False for relu-family specs, whose traces
    never read it.
    All numpy; device-put with ``put_stacked`` (P('pp') on the stage axis;
    per-slot column/row tp shards on a tp mesh). ``order[r]`` names the
    model stage stored at stacked row r (identity by default;
    ``interleave_order`` for virtual-stage layouts). ``tp`` pads the slot
    dims to tp multiples (slot_shapes) — the HOST layout stays the full
    global stack either way, so checkpoints are tp-independent.
    """
    dims = slot_shapes(spec, tp)
    S = spec.n_stages
    L = len(dims)
    order = list(range(S)) if order is None else list(order)
    assert sorted(order) == list(range(S)), "order must permute 0..S-1"
    Ws = [np.zeros((S, o, i), np.float32) for o, i in dims]
    bs = [np.zeros((S, o), np.float32) for o, _ in dims]
    active = np.zeros((S, L), np.bool_)
    relu = np.zeros((S, L), np.bool_)
    residual = np.zeros((S, L), np.bool_)
    head_mask = np.zeros((S, dims[-1][0]), np.bool_)
    for r, s in enumerate(order):
        sspec, sparams = spec.stages[s], params_list[s]
        res_flags = sspec.res_flags
        for l, layer in enumerate(sparams):
            out_d, in_d = layer["W"].shape
            Ws[l][r, :out_d, :in_d] = np.asarray(layer["W"])
            bs[l][r, :out_d] = np.asarray(layer["b"]).reshape(-1)
            active[r, l] = True
            relu[r, l] = sspec.relu_flags[l]
            residual[r, l] = res_flags[l]
        if sspec.has_head:
            head_mask[r, : sspec.out_dim] = True
    return (
        {"W": tuple(Ws), "b": tuple(bs)},
        {
            "active": active,
            "relu": relu,
            "residual": residual,
            "head_mask": head_mask,
        },
    )


def unstack_params(stacked, spec: ModelSpec, order=None):
    """Extract the logical ragged per-stage params back out (host numpy),
    inverting the stacking ``order`` so the result is in model-stage order."""
    Ws = [np.asarray(jax.device_get(w)) for w in stacked["W"]]
    bs = [np.asarray(jax.device_get(b)) for b in stacked["b"]]
    S = spec.n_stages
    order = list(range(S)) if order is None else list(order)
    row_of = {s: r for r, s in enumerate(order)}
    out = []
    for s, sspec in enumerate(spec.stages):
        r = row_of[s]
        layers = []
        for l in range(sspec.n_linears):
            in_d, out_d = sspec.local_sizes[l], sspec.local_sizes[l + 1]
            layers.append(
                {
                    "W": Ws[l][r, :out_d, :in_d].copy(),
                    "b": bs[l][r, :out_d].reshape(1, -1).copy(),
                }
            )
        out.append(layers)
    return out


def put_pp(tree, mesh: Mesh):
    """device_put a stage-stacked pytree with P('pp') sharding on the stage
    axis — the ONE place the stacked placement is defined for tp-replicated
    data (flags; params and state parts go through ``put_stacked_tree``,
    which adds the per-slot tp shards on a tp mesh)."""
    pp = NamedSharding(mesh, P("pp"))
    return jax.tree.map(lambda x: jax.device_put(x, pp), tree)


def stacked_param_specs(tp: int, L: int):
    """The per-slot PartitionSpecs of a stacked {"W", "b"} tree: P('pp')
    everywhere at tp == 1 (the historical placement, byte for byte); at
    tp > 1, Megatron shards — even slots split W on the OUT dim
    (column-parallel), odd slots on the IN dim (row-parallel), and every
    bias on its out dim. One definition shared by ``put_stacked_tree``
    and the executor's shard_map specs, so placement and program can
    never disagree."""
    if tp == 1:
        pp = P("pp")
        return {"W": (pp,) * L, "b": (pp,) * L}
    return {
        "W": tuple(
            P("pp", "tp", None) if l % 2 == 0 else P("pp", None, "tp")
            for l in range(L)
        ),
        "b": (P("pp", "tp"),) * L,
    }


def put_stacked_tree(stacked, mesh: Mesh):
    """device_put one stacked {"W": tuple, "b": tuple} tree with the mesh's
    per-slot shardings (``stacked_param_specs``). Params and every
    params-mirroring optimizer-state part go through here."""
    tp = mesh_tp(mesh)
    if tp == 1:
        return put_pp(stacked, mesh)
    specs = stacked_param_specs(tp, len(stacked["W"]))
    return {
        k: tuple(
            jax.device_put(x, NamedSharding(mesh, s))
            for x, s in zip(stacked[k], specs[k])
        )
        for k in ("W", "b")
    }


def put_stacked(stacked, flags, mesh: Mesh):
    """device_put stacked params + flags (see ``put_stacked_tree``/``put_pp``)."""
    return put_stacked_tree(stacked, mesh), put_pp(flags, mesh)


def init_stacked(spec: ModelSpec, mesh: Mesh, order=None):
    """Deterministic init, stacked + device_put with the mesh's sharding."""
    stacked, flags = stack_params(
        init_model(spec), spec, order=order, tp=mesh_tp(mesh)
    )
    return put_stacked(stacked, flags, mesh)


# ---------------------------------------------------------------------------
# ZeRO-1 optimizer-state sharding over dp
# ---------------------------------------------------------------------------
#
# With plain DP every replica holds the full optimizer state and repeats the
# identical update. ZeRO-1 (Rajbhandari et al. 2019) shards both over the dp
# axis: the gradient all-reduce becomes a reduce-scatter (each replica gets
# the summed gradient for 1/dp of the parameters), the update runs on that
# shard only, and an all-gather rebuilds the full parameters. Chunking
# commutes with elementwise optimizer math; the state_layout() protocol
# (optimizer.py) drives the flat layout — each 'params' state part (momentum
# velocity, Adam's m and v) becomes its own (pp, dp*chunk) array, 'scalar'
# parts (Adam's step count) replicate. On TPU both collectives ride ICI; the
# path uses IS reduce-scatter + all-gather internally, so the comm volume is
# the same while state memory and update FLOPs drop by dp. (The reference has
# no optimizer sharding at all — its DP engine is pipe.py:302-327.)
#
# Flat layout per pp-device: every W slot (V, o, i) then every b slot (V, o),
# concatenated flat and zero-padded to a dp multiple. Helpers below pack and
# unpack host-side state for layout-independent checkpoints.


def stacked_flat_len(spec: ModelSpec, pp: int, tp: int = 1) -> int:
    """Per-DEVICE flattened param count of the stacked layout (every W slot
    then every b slot, V virtual rows each; this rank's tp shard of each) —
    the ONE definition of the flat layout's size. ``zero1_flat_len``, the
    gradsync bucket planners and the audit's comms model all read it, so a
    layout change here propagates to every consumer at once. Under tp the
    per-device count shrinks by exactly tp (slot dims are tp-rounded, and
    both the column and row shard of a slot hold ``o*i/tp`` elements)."""
    dims = slot_shapes(spec, tp)
    V = spec.n_stages // pp
    return sum(V * o * i // tp for o, i in dims) + sum(
        V * (o // tp) for o, _ in dims
    )


def zero1_flat_len(spec: ModelSpec, mesh: Mesh):
    """(flat_len, chunk_size): per-device flattened param count and the
    padded per-dp-replica chunk size."""
    flat = stacked_flat_len(spec, mesh.shape["pp"], mesh_tp(mesh))
    return flat, -(-flat // mesh.shape["dp"])


def _zero1_device_rows(spec, mesh):
    """The zero1 flat layout's device-row iteration: yields ``(row_index,
    stage_slice, tp_rank)`` in (pp-major, tp-minor) order — exactly how
    ``P(('pp','tp'), 'dp')`` assigns the state matrix's rows to devices."""
    P_ = mesh.shape["pp"]
    tp = mesh_tp(mesh)
    V = spec.n_stages // P_
    for d in range(P_):
        for t in range(tp):
            yield d * tp + t, slice(d * V, (d + 1) * V), t


def _zero1_flatten_rows(stacked_np, spec, mesh):
    """Host-side: stacked {W,b} (numpy, stage axis S) -> (pp*tp, flat_len).
    Each row is one device's flat view — its V stage rows, and at tp > 1
    its column/row shard of each W slot and its out-shard of each b slot,
    in the exact order the in-program ``gvec``/``pvec`` concats produce."""
    tp = mesh_tp(mesh)
    dims = slot_shapes(spec, tp)
    rows = [None] * (mesh.shape["pp"] * tp)
    for r, sl, t in _zero1_device_rows(spec, mesh):
        parts = []
        for l, (o, i) in enumerate(dims):
            w = np.asarray(stacked_np["W"][l][sl])
            if tp > 1:
                o_s, i_s = o // tp, i // tp
                if l % 2 == 0:
                    w = w[:, t * o_s : (t + 1) * o_s, :]
                else:
                    w = w[:, :, t * i_s : (t + 1) * i_s]
            parts.append(np.ascontiguousarray(w).reshape(-1))
        for l, (o, _) in enumerate(dims):
            b = np.asarray(stacked_np["b"][l][sl])
            if tp > 1:
                o_s = o // tp
                b = b[:, t * o_s : (t + 1) * o_s]
            parts.append(np.ascontiguousarray(b).reshape(-1))
        rows[r] = np.concatenate(parts)
    return np.stack(rows)


def _zero1_unflatten_rows(arr, spec, mesh):
    """Host-side inverse of _zero1_flatten_rows: (pp*tp, >=flat_len) ->
    stacked (full global arrays — every device row writes its shard back)."""
    tp = mesh_tp(mesh)
    dims = slot_shapes(spec, tp)
    V = spec.n_stages // mesh.shape["pp"]
    Ws = [np.zeros((spec.n_stages, o, i), np.float32) for o, i in dims]
    bs = [np.zeros((spec.n_stages, o), np.float32) for o, _ in dims]
    for r, sl, t in _zero1_device_rows(spec, mesh):
        off = 0
        for l, (o, i) in enumerate(dims):
            o_s, i_s = o // tp, i // tp
            if tp == 1:
                n = V * o * i
                Ws[l][sl] = arr[r, off : off + n].reshape(V, o, i)
            elif l % 2 == 0:
                n = V * o_s * i
                Ws[l][sl, t * o_s : (t + 1) * o_s, :] = arr[
                    r, off : off + n
                ].reshape(V, o_s, i)
            else:
                n = V * o * i_s
                Ws[l][sl, :, t * i_s : (t + 1) * i_s] = arr[
                    r, off : off + n
                ].reshape(V, o, i_s)
            off += n
        for l, (o, _) in enumerate(dims):
            o_s = o // tp
            n = V * o_s
            bs[l][sl, t * o_s : (t + 1) * o_s] = arr[r, off : off + n].reshape(
                V, o_s
            )
            off += n
    return {"W": tuple(Ws), "b": tuple(bs)}


def _zero1_check_state(opt, csz):
    """zero1's flat layout requires each 'params' state part to come out of
    ``opt.init(chunk)`` as one chunk-shaped zeros array; reject anything the
    state_layout protocol doesn't describe, loudly."""
    from shallowspeed_tpu.optimizer import split_state

    probe = opt.init(np.zeros((csz,), np.float32))
    parts, scalars = split_state(opt, probe)
    for key, leaf in parts.items():
        if not (
            hasattr(leaf, "shape")
            and tuple(leaf.shape) == (csz,)
            and not np.any(np.asarray(leaf))
        ):
            raise ValueError(
                f"zero1: state part {key!r} of {type(opt).__name__} is not a "
                "zeros-initialized chunk mirror — its state_layout() does "
                "not match its init()"
            )
    for key, leaf in scalars.items():
        if np.ndim(leaf) != 0:
            raise ValueError(
                f"zero1: state part {key!r} of {type(opt).__name__} is "
                "declared 'scalar' but is not 0-d"
            )
    return parts, scalars


def zero1_part_spec(tp: int):
    """The PartitionSpec of one zero1 'params' state part: rows are devices
    of the (pp[, tp]) grid, columns chunk over dp. At tp == 1 this is the
    historical P('pp', 'dp') (byte-identical programs); at tp > 1 the row
    axis splits over BOTH non-dp axes — row ``p*tp + t`` is device (p, t),
    matching ``_zero1_device_rows``'s flat layout. The ONE definition:
    ``zero1_part_sharding`` (placement) and ``make_pipeline_step``'s
    shard_map state specs both read it, so device placement and program
    specs can never disagree."""
    if tp == 1:
        return P("pp", "dp")
    return P(("pp", "tp"), "dp")


def zero1_part_sharding(mesh: Mesh):
    """``zero1_part_spec`` bound to a mesh (see its docstring)."""
    return NamedSharding(mesh, zero1_part_spec(mesh_tp(mesh)))


def zero1_init_state(opt, spec: ModelSpec, mesh: Mesh):
    """Device-put initial ZeRO-1 optimizer state: a dict with one
    (pp[*tp], dp*chunk) array per 'params' state part — sharded so each
    device holds its own (1, chunk) shard — plus replicated 0-d arrays
    for 'scalar' parts; () for stateless optimizers."""
    from shallowspeed_tpu.optimizer import is_stateless

    flat, csz = zero1_flat_len(spec, mesh)
    if is_stateless(opt):
        return ()
    parts, scalars = _zero1_check_state(opt, csz)
    dp = mesh.shape["dp"]
    n_rows = mesh.shape["pp"] * mesh_tp(mesh)
    part_sh = zero1_part_sharding(mesh)
    rep_sh = NamedSharding(mesh, P())
    state = {
        key: jax.device_put(np.zeros((n_rows, dp * csz), np.float32), part_sh)
        for key in parts
    }
    state.update(
        {
            key: jax.device_put(np.asarray(leaf, np.float32), rep_sh)
            for key, leaf in scalars.items()
        }
    )
    return state


def zero1_state_to_logical(state, opt, spec: ModelSpec, mesh: Mesh, order=None):
    """ZeRO-1 state dict -> {"parts": {key: ragged_list}, "scalars":
    {key: float}} mirroring params (for layout-independent checkpoints);
    None for stateless state."""
    if isinstance(state, tuple) and state == ():
        return None
    layout = opt.state_layout()
    flat, _ = zero1_flat_len(spec, mesh)
    parts, scalars = {}, {}
    for key, kind in layout.items():
        if kind == "params":
            arr = np.asarray(jax.device_get(state[key]))[:, :flat]
            stacked = _zero1_unflatten_rows(arr, spec, mesh)
            parts[key] = unstack_params(stacked, spec, order=order)
        else:
            scalars[key] = float(jax.device_get(state[key]))
    return {"parts": parts, "scalars": scalars}


def _zero1_state_rows(logical_part, spec, mesh, order):
    """Stack one logical state part and flatten it into the zero1 device
    rows (tp-aware)."""
    stacked, _ = stack_params(logical_part, spec, order=order, tp=mesh_tp(mesh))
    return _zero1_flatten_rows(stacked, spec, mesh)


def zero1_state_from_logical(logical, opt, spec: ModelSpec, mesh: Mesh, order=None):
    """Inverse: logical {"parts", "scalars"} dict -> device-put state."""
    if logical is None:
        return zero1_init_state(opt, spec, mesh)
    flat, csz = zero1_flat_len(spec, mesh)
    dp = mesh.shape["dp"]
    layout = opt.state_layout()
    part_sh = zero1_part_sharding(mesh)
    rep_sh = NamedSharding(mesh, P())
    n_rows = mesh.shape["pp"] * mesh_tp(mesh)
    state = {}
    for key, kind in layout.items():
        if kind == "params":
            rows = _zero1_state_rows(logical["parts"][key], spec, mesh, order)
            padded = np.zeros((n_rows, dp * csz), np.float32)
            padded[:, :flat] = rows
            state[key] = jax.device_put(padded, part_sh)
        else:
            state[key] = jax.device_put(
                np.asarray(logical["scalars"][key], np.float32), rep_sh
            )
    return state


# ---------------------------------------------------------------------------
# ZeRO-2/3: the block-cyclic per-slot shard layout over dp
# ---------------------------------------------------------------------------
#
# ZeRO-1 shards only the optimizer STATE: the program still concatenates the
# full flat gradient (gvec) and the full flat params (pvec) before the one
# reduce-scatter / chunk-slice, so three flat-sized temporaries coexist at
# the tail. The higher stages kill those temporaries by making the shard
# layout PER LAYER SLOT instead of per flat vector:
#
#   every slot (V virtual rows of sz elements; W slots then b slots, the
#   same order as the flat layout) pads each row to dp*k columns
#   (k = ceil(sz/dp)) and deals column-block d to dp rank d. Rank d's local
#   shard is the concatenation over slots of its (V, k) blocks flattened
#   v-major — csz3 = sum_slots V*k elements per rank.
#
# Why block-cyclic and not the zero1 flat chunking: a slot's gradient slab
# (V, sz) reduce-scatters DIRECTLY into this layout (pad the row, deal the
# column blocks — one collective per slot, no flat concat), and a single
# row's gradient reduce-scatters into ONE (k,) segment of the shard — which
# is what lets ZeRO-3 sync per tick from inside the scan. The column-block
# deal is exactly the (dp, chunk) column view ``gradsync.
# psum_scatter_bucketed`` already emits, so byte-bucket plans compose
# (mode "zero2": ranges within a slot's [0, V*k) columns).
#
# ZeRO-2 = params still replicated (stacked {W, b} as ever) + gradients
# reduce-scattered per slot at the tail anchor + optimizer state sharded in
# this layout. Elementwise collectives: each element's dp-sum lands with
# identical bits wherever it is scattered, so ZeRO-2 weights are BITWISE
# equal to ZeRO-1's at a fixed layout for elementwise optimizer math (the
# clip/grad-norm scalar partitions its partial sums differently — pin
# bitwise equality on clip-free runs).
#
# ZeRO-3 = params AT REST in this layout ({"P": (pp*tp, dp*csz3)} under
# ``zero1_part_spec``) — each tick branch all-gathers just the active
# chunk's slot segments (under tp only the 1/tp local shard, since the
# layout is built from tp-local slot shapes), uses them, and lets them die
# with the branch; the backward reduce-scatters each tick's slot gradients
# immediately into the persistent (csz3,) gradient shard. The per-tick sync
# reassociates the microbatch/replica sum order (sum_m sum_d vs the slab
# path's sum_d sum_m), hence ZeRO-3's tolerance-not-bitwise contract.
#
# Host helpers below transform between the flat device rows (the zero1
# layout) and the block-cyclic rows, so checkpoints stay logical and
# layout-independent.


class ZeroSlot(NamedTuple):
    """One layer slot's geometry in the block-cyclic dp-shard layout."""

    kind: str  # "W" | "b"
    layer: int  # slot index within its kind
    rows: int  # V virtual chunk rows
    shape: tuple  # per-row tp-LOCAL shape: (o, i) W shard or (o,) b shard
    sz: int  # elements per row = prod(shape)
    k: int  # per-dp-rank columns = ceil(sz / dp)
    off: int  # start within a rank's csz3 block (cumulative V*k)
    flat_off: int  # start within the flat layout (cumulative V*sz)


def zero_block_slots(spec: ModelSpec, pp: int, dp: int, tp: int = 1):
    """(slots, csz3): the per-slot block-cyclic geometry and the per-rank
    shard length. Slot order == the flat layout's (every W slot then every
    b slot), so ``flat_off`` walks ``stacked_flat_len`` exactly."""
    dims = slot_shapes(spec, tp)
    V = spec.n_stages // pp
    slots = []
    off = flat_off = 0
    for l, (o, i) in enumerate(dims):
        if tp == 1:
            shape = (o, i)
        elif l % 2 == 0:  # column-parallel slot: out-dim sharded
            shape = (o // tp, i)
        else:  # row-parallel slot: in-dim sharded
            shape = (o, i // tp)
        sz = shape[0] * shape[1]
        k = -(-sz // dp)
        slots.append(ZeroSlot("W", l, V, shape, sz, k, off, flat_off))
        off += V * k
        flat_off += V * sz
    for l, (o, _) in enumerate(dims):
        sz = o // tp
        k = -(-sz // dp)
        slots.append(ZeroSlot("b", l, V, (sz,), sz, k, off, flat_off))
        off += V * k
        flat_off += V * sz
    return tuple(slots), off


def zero_block_len(spec: ModelSpec, mesh: Mesh):
    """(flat_len, csz3): the flat per-device param count and the
    block-cyclic per-dp-rank shard length (>= ceil(flat/dp); per-slot
    padding rounds each slot separately)."""
    slots, csz3 = zero_block_slots(
        spec, mesh.shape["pp"], mesh.shape["dp"], mesh_tp(mesh)
    )
    return slots[-1].flat_off + slots[-1].rows * slots[-1].sz, csz3


def _zb_scatter_rows(g2d, dp, k):
    """(V, sz) slot rows -> the (dp, V*k) per-rank column-block deal: pad
    each row to dp*k, deal column block d to output row d (row v lands
    v-major at columns [v*k, (v+1)*k) of its rank). Works on numpy or jnp
    arrays (pure reshape/transpose)."""
    V, sz = g2d.shape
    mod = np if isinstance(g2d, np.ndarray) else jnp
    pad = mod.pad(g2d, ((0, 0), (0, dp * k - sz)))
    return pad.reshape(V, dp, k).transpose(1, 0, 2).reshape(dp, V * k)


def _zb_unscatter_rows(mat, V, k, sz):
    """(dp, V*k) -> (V, sz): inverse of ``_zb_scatter_rows`` (drops the
    per-row padding)."""
    dp = mat.shape[0]
    return (
        mat.reshape(dp, V, k).transpose(1, 0, 2).reshape(V, dp * k)[:, :sz]
    )


def _zb_deal_view(g2d, dp, k):
    """(V, sz) slot rows -> the (V, dp, k) deal VIEW: the same per-rank
    column deal as ``_zb_scatter_rows`` but as a pad + reshape only —
    element (v, d, j) is padded row v's column d*k+j, so a dp-collective
    on axis 1 touches exactly the elements the (dp, V*k) layout's axis-0
    collective does, without ever materializing the transposed full-slot
    slab (the ZeRO-2 tail's peak-HBM discipline: live temporaries stay
    shard-sized, not model-sized)."""
    V, sz = g2d.shape
    return jnp.pad(g2d, ((0, 0), (0, dp * k - sz))).reshape(V, dp, k)


def _zero_block_rows_from_flat(flat_rows, slots, dp, csz3):
    """Host-side: flat device rows (n_rows, >=flat_len) -> block-cyclic
    rows (n_rows, dp*csz3), where columns [d*csz3, (d+1)*csz3) are rank d's
    shard (so ``zero1_part_spec`` column-chunking lands each rank its own
    block)."""
    n_rows = flat_rows.shape[0]
    out = np.zeros((n_rows, dp * csz3), np.float32)
    for s in slots:
        seg = flat_rows[:, s.flat_off : s.flat_off + s.rows * s.sz]
        for r in range(n_rows):
            mat = _zb_scatter_rows(
                np.asarray(seg[r], np.float32).reshape(s.rows, s.sz), dp, s.k
            )
            for d in range(dp):
                a = d * csz3 + s.off
                out[r, a : a + s.rows * s.k] = mat[d]
    return out


def _zero_flat_from_block_rows(block_rows, slots, dp, csz3, flat):
    """Host-side inverse of ``_zero_block_rows_from_flat``."""
    n_rows = block_rows.shape[0]
    out = np.zeros((n_rows, flat), np.float32)
    for s in slots:
        for r in range(n_rows):
            mat = np.stack(
                [
                    block_rows[
                        r, d * csz3 + s.off : d * csz3 + s.off + s.rows * s.k
                    ]
                    for d in range(dp)
                ]
            )
            full = _zb_unscatter_rows(mat, s.rows, s.k, s.sz)
            out[r, s.flat_off : s.flat_off + s.rows * s.sz] = full.reshape(-1)
    return out


def zero_block_flatten_rows(stacked_np, spec, mesh):
    """Host-side: stacked {W,b} (numpy) -> (pp*tp, dp*csz3) block-cyclic
    device rows, ready for ``zero1_part_sharding`` placement (the ZeRO-3
    at-rest param layout)."""
    dp = mesh.shape["dp"]
    slots, csz3 = zero_block_slots(
        spec, mesh.shape["pp"], dp, mesh_tp(mesh)
    )
    return _zero_block_rows_from_flat(
        _zero1_flatten_rows(stacked_np, spec, mesh), slots, dp, csz3
    )


def zero_block_unflatten_rows(arr, spec, mesh):
    """Host-side inverse: (pp*tp, dp*csz3) -> stacked {W,b} full global
    arrays."""
    dp = mesh.shape["dp"]
    slots, csz3 = zero_block_slots(
        spec, mesh.shape["pp"], dp, mesh_tp(mesh)
    )
    flat = stacked_flat_len(spec, mesh.shape["pp"], mesh_tp(mesh))
    return _zero1_unflatten_rows(
        _zero_flat_from_block_rows(arr, slots, dp, csz3, flat), spec, mesh
    )


def zero_block_init_state(opt, spec: ModelSpec, mesh: Mesh):
    """Device-put initial ZeRO-2/3 optimizer state: like
    ``zero1_init_state`` but columns are the block-cyclic csz3 shard."""
    from shallowspeed_tpu.optimizer import is_stateless

    _, csz3 = zero_block_len(spec, mesh)
    if is_stateless(opt):
        return ()
    parts, scalars = _zero1_check_state(opt, csz3)
    dp = mesh.shape["dp"]
    n_rows = mesh.shape["pp"] * mesh_tp(mesh)
    part_sh = zero1_part_sharding(mesh)
    rep_sh = NamedSharding(mesh, P())
    state = {
        key: jax.device_put(
            np.zeros((n_rows, dp * csz3), np.float32), part_sh
        )
        for key in parts
    }
    state.update(
        {
            key: jax.device_put(np.asarray(leaf, np.float32), rep_sh)
            for key, leaf in scalars.items()
        }
    )
    return state


def zero_block_state_to_logical(state, opt, spec: ModelSpec, mesh: Mesh, order=None):
    """ZeRO-2/3 state dict -> logical {"parts", "scalars"} (for
    layout-independent checkpoints); None for stateless state."""
    if isinstance(state, tuple) and state == ():
        return None
    layout = opt.state_layout()
    parts, scalars = {}, {}
    for key, kind in layout.items():
        if kind == "params":
            arr = np.asarray(jax.device_get(state[key]))
            stacked = zero_block_unflatten_rows(arr, spec, mesh)
            parts[key] = unstack_params(stacked, spec, order=order)
        else:
            scalars[key] = float(jax.device_get(state[key]))
    return {"parts": parts, "scalars": scalars}


def zero_block_state_from_logical(logical, opt, spec: ModelSpec, mesh: Mesh, order=None):
    """Inverse: logical {"parts", "scalars"} dict -> device-put ZeRO-2/3
    state."""
    if logical is None:
        return zero_block_init_state(opt, spec, mesh)
    layout = opt.state_layout()
    part_sh = zero1_part_sharding(mesh)
    rep_sh = NamedSharding(mesh, P())
    dp = mesh.shape["dp"]
    slots, csz3 = zero_block_slots(
        spec, mesh.shape["pp"], dp, mesh_tp(mesh)
    )
    state = {}
    for key, kind in layout.items():
        if kind == "params":
            rows = _zero1_state_rows(logical["parts"][key], spec, mesh, order)
            state[key] = jax.device_put(
                _zero_block_rows_from_flat(rows, slots, dp, csz3), part_sh
            )
        else:
            state[key] = jax.device_put(
                np.asarray(logical["scalars"][key], np.float32), rep_sh
            )
    return state


# ---------------------------------------------------------------------------
# The tick-program step builder
# ---------------------------------------------------------------------------


def _fit(a, width):
    """Slice or zero-pad the last dim to ``width`` (exact under the padding
    invariant: dropped columns are always zero)."""
    cur = a.shape[-1]
    if cur == width:
        return a
    if cur > width:
        return a[..., :width]
    return jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(0, width - cur)])


def _stage_fwd(
    Ws, bs, active, relu, dims, x, precision, kernel_backend="xla",
    act="relu", residual=None,
):
    """Forward through the per-slot stacks; returns (out, xs, masks) where
    xs[l]: (mb, in_l) and masks[l]: (mb, out_l).

    ``kernel_backend="pallas"`` runs each slot as one fused Pallas unit
    (pallas_ops.linear_flag_fwd): the traced relu flag rides into the kernel
    as a scalar operand, so the chunk-uniform layer loop needs no static
    per-stage specialization. Same math (flag-selected relu on z = x@w.T+b,
    mask = z > 0) either way.

    ``act`` is the spec's STATIC activation family: "relu" traces exactly
    the historical program (bool bitmask residuals, no residual-add
    expressions anywhere — byte-identical); "gelu" stores the f32
    derivative ``gelu_grad_mult(z)`` in the mask slot (1.0 where the flag
    is off) and adds the ``residual`` flags' skip connections
    (y_l += x_{l-1}, exact under _fit because residual widths are equal by
    spec construction and padding is exact zeros)."""
    xs, masks = [], []
    x_prev = None
    for l, (o, i) in enumerate(dims):
        x_l = _fit(x, i)
        if kernel_backend == "pallas":
            from shallowspeed_tpu import pallas_ops

            y_act, mask_f = pallas_ops.linear_flag_fwd(
                x_l, Ws[l], jnp.reshape(bs[l], (1, -1)), relu[l],
                precision=precision,
            )
            xs.append(x_l)
            masks.append(mask_f > 0)
        elif act == "gelu":
            y = ops.linear(x_l, Ws[l], bs[l], precision=precision)
            xs.append(x_l)
            masks.append(jnp.where(relu[l], ops.gelu_grad_mult(y), 1.0))
            y_act = jnp.where(relu[l], ops.gelu(y), y)
            if l > 0:
                y_act = y_act + jnp.where(
                    residual[l], _fit(x_prev, o), 0.0
                )
        else:
            y = ops.linear(x_l, Ws[l], bs[l], precision=precision)
            xs.append(x_l)
            masks.append(y > 0)
            y_act = jnp.where(relu[l], ops.relu(y), y)
        x_prev = x_l
        x = jnp.where(active[l], y_act, _fit(x_l, o))
    return x, tuple(xs), tuple(masks)


def _stage_bwd(
    Ws, active, relu, dims, xs, masks, g, precision, kernel_backend="xla",
    act="relu", residual=None,
):
    """Backward through the per-slot stacks; returns (dx, gWs, gbs).

    Gelu family: ``masks`` carry the stashed f32 derivative values, so the
    effective-grad expression is the SAME ``g * mask`` character string as
    relu's; residual skip grads add the NEXT slot's incoming grad to this
    slot's dx (x_{l-1} fed both linear l and the residual at slot l's
    output)."""
    L = len(dims)
    gWs, gbs = [None] * L, [None] * L
    g_prev = None
    for l in reversed(range(L)):
        o, i = dims[l]
        g_l = _fit(g, o)
        if kernel_backend == "pallas":
            from shallowspeed_tpu import pallas_ops

            dx, dw, db2 = pallas_ops.linear_flag_bwd(
                g_l, masks[l].astype(jnp.float32), xs[l], Ws[l], relu[l],
                precision=precision,
            )
            db = jnp.reshape(db2, (-1,))
        else:
            g_eff = jnp.where(relu[l], g_l * masks[l], g_l)
            dx, dw, db = ops.linear_grad(g_eff, xs[l], Ws[l], precision=precision)
            if act == "gelu" and l + 1 < L:
                dx = dx + jnp.where(residual[l + 1], _fit(g_prev, i), 0.0)
        gWs[l] = jnp.where(active[l], dw, 0.0)
        gbs[l] = jnp.where(active[l], db, 0.0)
        g = jnp.where(active[l], dx, _fit(g_l, i))
        g_prev = g_l
    return g, tuple(gWs), tuple(gbs)


def _stage_bwd_input(Ws, active, relu, dims, masks, g, precision,
                     act="relu", residual=None):
    """The relay-critical half of the split backward: the dgrad chain only.

    Returns ``(dx, g_effs)`` — the input gradient the upstream stage waits
    for, plus the per-slot effective output-grads (the relu-masked ``g`` at
    each slot, the SAME tensors the combined backward feeds its wgrad
    matmuls). Those are free intermediates of the dx chain; the executor
    stashes them so the deferred B-weight never recomputes a dgrad matmul.
    Bit-parity: each slot's ``g_eff``/``dx`` expressions are character-
    identical to ``_stage_bwd``'s, so the downstream wgrads are too.
    Residual skip grads (gelu family) ride the dx chain here too — they
    never touch ``g_eff``, so the deferred B-weight is family-agnostic.
    """
    L = len(dims)
    g_effs = [None] * L
    g_prev = None
    for l in reversed(range(L)):
        o, i = dims[l]
        g_l = _fit(g, o)
        g_eff = jnp.where(relu[l], g_l * masks[l], g_l)
        g_effs[l] = g_eff
        dx = ops.linear_grad_input(g_eff, Ws[l], precision=precision)
        if act == "gelu" and l + 1 < L:
            dx = dx + jnp.where(residual[l + 1], _fit(g_prev, i), 0.0)
        g = jnp.where(active[l], dx, _fit(g_l, i))
        g_prev = g_l
    return g, tuple(g_effs)


def _stage_bwd_weight(active, dims, xs, g_effs, precision):
    """The deferred half of the split backward: per-slot wgrads from the
    stashed activations and the stashed effective output-grads. Slots are
    independent (no chain), and the expressions match ``_stage_bwd``'s
    wgrad leg exactly — bit-identical per-microbatch contributions."""
    L = len(dims)
    gWs, gbs = [None] * L, [None] * L
    for l in range(L):
        dw, db = ops.linear_grad_weight(g_effs[l], xs[l], precision=precision)
        gWs[l] = jnp.where(active[l], dw, 0.0)
        gbs[l] = jnp.where(active[l], db, 0.0)
    return tuple(gWs), tuple(gbs)


# ---------------------------------------------------------------------------
# Megatron-sharded (tp > 1) stage functions
#
# Slot parity is the sharding: EVEN slots are column-parallel (W split on the
# out dim — the forward contracts the full input locally, no collective),
# ODD slots are row-parallel (W split on the in dim over the column slot's
# output shard — partial products summed by ONE psum over 'tp', the Megatron
# g-operator). The backward mirrors: row slots are local, column slots psum
# their dx partials (the f-operator) — exactly 2 all-reduces per layer pair
# per fwd+bwd pass (``tp_allreduce_sites`` is the audited site list).
#
# Exactness notes:
# - every psum that reassembles a sharded value (inactive-slot passthrough,
#   the closing stage-boundary gather, the scattered row-parallel bias) sums
#   contributions where each element is written by exactly ONE rank and the
#   others add exact zeros — exact data movement, like _fit;
# - the psums that sum PARTIAL PRODUCTS (row forward, column dx) split a
#   contraction across ranks, which reassociates the fp sum: tp > 1 layouts
#   therefore match the sequential oracle under the repo's standard
#   cross-layout tolerance (exactly like a different dp width reassociating
#   the gradient all-reduce — docs/numerics.md), while tp=1 stays byte-
#   identical (these functions are never traced at tp == 1) and same-layout
#   A/B knobs at fixed tp (bucketed vs anchor sync, split vs combined
#   backward, fused-run vs step loop) remain bitwise;
# - these psums sit inside ``lax.switch`` branches; the branch predicate is
#   the stage's op code, identical for every member of a tp group (same
#   (dp, pp) coordinates), so each all-reduce group executes uniformly.
# ---------------------------------------------------------------------------


def _tp_shard(a, t, w):
    """Rank t's width-``w`` slice of a full-width last dim (exact: column
    selection). The inverse of ``_tp_scatter``."""
    return lax.dynamic_slice_in_dim(a, t * w, w, axis=-1)


def _tp_scatter(a_loc, t, full_w):
    """Place rank t's shard at its column offset in a zero full-width
    array — a psum over 'tp' of every rank's scatter IS the all-gather
    (each column written by exactly one rank; the rest add exact 0.0)."""
    z = jnp.zeros(a_loc.shape[:-1] + (full_w,), a_loc.dtype)
    return lax.dynamic_update_slice_in_dim(z, a_loc, t * a_loc.shape[-1], axis=-1)


def _stage_fwd_tp(Ws, bs, active, relu, dims, x, precision, tp_idx, tp,
                  act="relu", residual=None):
    """Megatron-sharded forward through the per-slot stacks (tp > 1).

    Returns ``(out_full, xs, masks)``: the stage output completed to full
    width (the boundary — relay payload or softmax head — never sees a
    shard), plus the residuals in the representation the backward
    consumes — ``xs[l]`` is slot l's input as its wgrad contracts it (full
    for column slots, this rank's shard for row slots), ``masks[l]`` the
    pre-activation bitmask as its dgrad masks it (rank-sharded for column
    slots, full post-psum for row slots).

    Inactive slots keep the representation state machine running: an even
    passthrough takes the rank's shard of the fitted activation, an odd
    passthrough scatters the shard back to full width THROUGH the slot's
    own psum (the inactive branch rides the same collective — uniform
    collectives, masked payloads, the executor's house idiom).

    Gelu family (``act="gelu"``): the mask slots carry the f32 derivative
    values in the same representation (sharded pre-activation at column
    slots, full post-psum at row slots), and the ``residual`` skip adds
    land at ROW slots only (the zoo's residual flags sit on odd global
    parity, which even per-stage slices preserve locally) AFTER the slot's
    psum — both operands are full-width there, so the add is replicated,
    never collective-scaled."""
    L = len(dims)
    xs, masks = [], []
    x_prev = None
    for l, (o, i) in enumerate(dims):
        if l % 2 == 0:  # column-parallel: full input, sharded output
            x_l = _fit(x, i)
            z_loc = ops.linear(x_l, Ws[l], bs[l], precision=precision)
            xs.append(x_l)
            if act == "gelu":
                masks.append(jnp.where(relu[l], ops.gelu_grad_mult(z_loc), 1.0))
                y_loc = jnp.where(relu[l], ops.gelu(z_loc), z_loc)
            else:
                masks.append(z_loc > 0)
                y_loc = jnp.where(relu[l], ops.relu(z_loc), z_loc)
            x_prev = x_l
            x = jnp.where(
                active[l], y_loc, _tp_shard(_fit(x_l, o), tp_idx, o // tp)
            )
        else:  # row-parallel: sharded input, one psum, full output
            z_part = jnp.matmul(x, Ws[l].T, precision=precision)
            b_full = _tp_scatter(jnp.reshape(bs[l], (-1,)), tp_idx, o)
            pre = jnp.where(
                active[l],
                z_part + b_full[None, :],
                _fit(_tp_scatter(x, tp_idx, i), o),
            )
            z_full = lax.psum(pre, "tp")
            xs.append(x)
            if act == "gelu":
                masks.append(
                    jnp.where(relu[l], ops.gelu_grad_mult(z_full), 1.0)
                )
                y = jnp.where(relu[l], ops.gelu(z_full), z_full)
                y = y + jnp.where(residual[l], _fit(x_prev, o), 0.0)
            else:
                masks.append(z_full > 0)
                y = jnp.where(relu[l], ops.relu(z_full), z_full)
            x = jnp.where(active[l], y, z_full)
    if (L - 1) % 2 == 0:
        # trailing column slot left the stage output sharded: complete it
        # (the closing gather of tp_allreduce_sites' forward list)
        x = lax.psum(_tp_scatter(x, tp_idx, dims[-1][0]), "tp")
    return x, tuple(xs), tuple(masks)


def _stage_bwd_input_tp(Ws, active, relu, dims, masks, g, precision, tp_idx, tp,
                        act="relu", residual=None):
    """The dgrad chain of the Megatron backward (tp > 1): the split
    B-input, and — composed with ``_stage_bwd_weight_tp`` below — the
    combined backward's first half. Returns ``(dx_full, g_effs)``; the
    per-slot effective output-grads are stashed in the SAME representation
    the masks use (sharded for column slots, full for row slots).

    Gelu residual grads land at COLUMN slots only (the skip's producer is
    the even slot's full-width input), AFTER the slot's dx psum — both
    operands full-width and replicated, exactly mirroring the forward."""
    L = len(dims)
    g_effs = [None] * L
    g_prev = None
    if (L - 1) % 2 == 0:
        # the stage output was completed to full width; the trailing
        # column slot's dgrad consumes this rank's shard of its grad
        o = dims[-1][0]
        g = _tp_shard(_fit(g, o), tp_idx, o // tp)
    for l in reversed(range(L)):
        o, i = dims[l]
        if l % 2 == 0:  # column-parallel: sharded g, psum'd full dx
            g_eff = jnp.where(relu[l], g * masks[l], g)
            g_effs[l] = g_eff
            part = jnp.matmul(g_eff, Ws[l], precision=precision)
            pre = jnp.where(
                active[l], part, _fit(_tp_scatter(g, tp_idx, o), i)
            )
            g = lax.psum(pre, "tp")
            if act == "gelu" and l + 1 < L:
                g = g + jnp.where(residual[l + 1], _fit(g_prev, i), 0.0)
        else:  # row-parallel: full g, local sharded dx
            g_l = _fit(g, o)
            g_eff = jnp.where(relu[l], g_l * masks[l], g_l)
            g_effs[l] = g_eff
            dx = jnp.matmul(g_eff, Ws[l], precision=precision)
            g = jnp.where(
                active[l], dx, _tp_shard(_fit(g_l, i), tp_idx, i // tp)
            )
            g_prev = g_l
    return g, tuple(g_effs)


def _stage_bwd_weight_tp(active, dims, xs, g_effs, precision, tp_idx, tp):
    """The wgrad half of the Megatron backward (tp > 1): every product is
    LOCAL (dW contracts over the microbatch rows, never over a sharded
    dim), so the deferred B-weight stays collective-free under tp too.
    Row-slot biases are stored sharded; their db is the rank's slice of
    the full row-sum (exact column selection)."""
    L = len(dims)
    gWs, gbs = [None] * L, [None] * L
    for l in range(L):
        o, _ = dims[l]
        dw = jnp.matmul(g_effs[l].T, xs[l], precision=precision)
        if l % 2 == 0:
            db = g_effs[l].sum(axis=0)
        else:
            db = _tp_shard(g_effs[l].sum(axis=0), tp_idx, o // tp)
        gWs[l] = jnp.where(active[l], dw, 0.0)
        gbs[l] = jnp.where(active[l], db, 0.0)
    return tuple(gWs), tuple(gbs)


def _stage_bwd_tp(Ws, active, relu, dims, xs, masks, g, precision, tp_idx, tp,
                  act="relu", residual=None):
    """Combined Megatron backward: the literal composition of the two
    halves (same composition contract as ops.linear_grad — split and
    combined schedules can never disagree, at any tp)."""
    dx, g_effs = _stage_bwd_input_tp(
        Ws, active, relu, dims, masks, g, precision, tp_idx, tp,
        act=act, residual=residual,
    )
    gWs, gbs = _stage_bwd_weight_tp(
        active, dims, xs, g_effs, precision, tp_idx, tp
    )
    return dx, gWs, gbs


def make_pipeline_step(
    mesh: Mesh,
    spec: ModelSpec,
    prog: TickProgram,
    mubatch_size: int,
    opt=None,
    precision=ops.DEFAULT_PRECISION,
    jit=True,
    tick_unroll=1,
    zero1=False,
    zero=None,
    clip_norm=None,
    kernel_backend="xla",
    with_grad_norm=False,
    with_step_stats=False,
    with_digests=False,
    grad_bucket_bytes=0,
):
    """Build the jitted SPMD step executing one TickProgram over the mesh.

    Training (prog.is_training, opt required):
        step(stacked, flags, opt_state, x, y) -> (stacked, opt_state, loss)
      x: (global_batch, in_dim) sharded P('dp'); y: (global_batch, out_dim).
      opt_state is threaded exactly like the sequential trainer's, so
      stateful optimizers (momentum et al.) behave identically on every
      layout; loss is the global-batch MSE (computed on the fly at the head
      stage — an observability bonus the reference never offers).

    ``zero1``: shard the optimizer update over dp — reduce_scatter the
    gradients, update 1/dp of the (flattened) params per replica with 1/dp
    of the optimizer state, all_gather the result (see the ZeRO-1 section
    above; opt_state must come from ``zero1_init_state``). Exact for
    elementwise optimizers; bit-identical math to the plain path up to
    collective reassociation.

    ``zero``: the full dp-axis stage selector {0, 1, 2, 3} superseding the
    ``zero1`` boolean (``zero=1`` IS the zero1 path, verbatim). Stage 2
    keeps params replicated but reduce-scatters the gradient PER LAYER
    SLOT into the block-cyclic shard layout (see the ZeRO-2/3 section
    above) — the flat gradient/param concats never materialize; opt_state
    must come from ``zero_block_init_state``. Stage 3 additionally shards
    the params at rest: ``stacked`` becomes ``{"P": (pp*tp, dp*csz3)}``
    under ``zero1_part_spec``, every tick branch all-gathers just the
    active chunk's slot segments, and the backward reduce-scatters each
    tick's gradients immediately (per-tick sync => the tolerance-not-
    bitwise contract; stages 0-2 stay bitwise-comparable).

    ``clip_norm``: optional global-norm gradient clipping before the update.
    The norm is GLOBAL over every parameter of the model: the local squared
    sum is psum'd over ``pp`` (and, under zero1, over ``dp`` where the
    summed gradient lives chunked) — padded entries are exactly zero, so the
    stacked norm equals the logical norm. The norm always reads the
    POST-SYNC gradient, so it is identical under both sync modes.

    ``grad_bucket_bytes``: 0 (default) keeps the legacy gradient-sync
    anchor — one whole-tree ``lax.psum`` over ``dp`` (one flat
    ``psum_scatter`` under zero1). A positive byte budget switches to the
    bucketed sync (parallel/gradsync.py): the gradient is greedily packed
    into backward-ordered buckets of at most this many bytes and each
    bucket is synced by its OWN collective, giving XLA's scheduler
    independent communication ops to overlap with the update's compute.
    Bitwise identical to the anchor on every layout (elementwise
    reductions; tested).

    ``with_grad_norm`` (training only): telemetry aux — the step returns a
    FOURTH output, the pre-clip global gradient norm (replicated scalar,
    same reduction geometry as the clip's). Pure data flow out of the
    shard_map, so the fused step program is unchanged in structure.

    ``with_step_stats`` (training only; implies the grad-norm output): the
    flight-recorder aux — a FIFTH output, the post-update global parameter
    norm (replicated scalar; padded entries are exactly zero, so the
    stacked norm IS the logical norm, psum'd over ``pp``). Together with
    the per-step loss these are the scalars the numerics health monitor
    checks on host after each epoch's single readback.

    ``with_digests`` (training only): the numerics-provenance aux — one
    EXTRA trailing output, a dict of layout-independent ``(S, L)`` grids
    (stacked-row x layer-slot): ``crc_w``/``crc_b`` are the per-block
    uint32 wrap-around checksums of the POST-update float32 param bits
    (bitcast, so bit-identical runs match bit for bit; psum on uint32
    wraps mod 2^32, and padding is exactly +0.0 = 0x00000000, so the
    psum'd stacked checksum EQUALS the logical per-layer checksum —
    ``utils.block_checksum``); ``pnorm_w``/``pnorm_b`` are post-update
    per-block L2 norms and ``gnorm_w``/``gnorm_b`` the post-sync
    PRE-clip per-block grad norms. Each device scatters its local rows
    into the grid and one psum over the param-sharded axes replicates
    the full matrix — pure data flow, no host callbacks.

    Inference:
        step(stacked, flags, x) -> preds (global_eval_batch, out_width) P('dp')

    Activation residuals live in stash slots assigned by the lowering, so a
    schedule's real peak activation memory is its scheduling property:
    GPipe allocates M slots, PipeDream-Flush min(M, depth) — the 1F1B memory
    advantage is physical buffer sizes here, not just a diagram.

    ``kernel_backend``: "xla" (default) or "pallas" — the per-slot compute
    unit inside every tick. "pallas" uses the flag-operand fused kernels
    (pallas_ops.linear_flag_fwd/bwd; the traced relu flag is a kernel
    operand, so one kernel serves every stage/chunk). Slots within the
    single-block VMEM budget run as one block; larger slots auto-dispatch
    to the grid-tiled flag kernels (pallas_ops.flag_kernels_fit reports
    the regime per slot).

    Tensor parallelism is a MESH property, not a parameter: when ``mesh``
    carries a ``tp`` axis the per-slot stacks arrive Megatron-sharded
    (``stacked_param_specs``) and the tick branches dispatch the tp stage
    functions instead of the flat ones (xla backend only). Everything
    else — tick tables, relays, gradient sync modes, the optimizer tail —
    is unchanged in structure; the cross-device norm reductions simply
    span ('pp','tp').
    """
    if kernel_backend not in ("xla", "pallas"):
        raise ValueError(f"unknown kernel_backend {kernel_backend!r}")
    if zero is None:
        zero = 1 if zero1 else 0
    else:
        zero = int(zero)
        if zero1 and zero != 1:
            raise ValueError(
                f"conflicting dp-stage selectors: zero1=True but zero={zero}"
            )
    if zero not in (0, 1, 2, 3):
        raise ValueError(f"zero must be one of 0/1/2/3, got {zero}")
    zero1 = zero == 1  # the legacy flag IS stage 1 — that path is verbatim
    if zero == 3 and kernel_backend == "pallas":
        raise ValueError(
            "zero=3 all-gathers parameter segments inside every tick "
            "branch; the fused pallas flag kernels take whole resident "
            "slots — use kernel_backend='xla' with --zero 3"
        )
    if zero == 3 and grad_bucket_bytes:
        raise ValueError(
            "zero=3 syncs gradients per tick (one reduce-scatter per layer "
            "slot inside the scan); the grad_bucket_bytes knob shapes the "
            "tail sync only and has nothing to bucket at stage 3"
        )
    tp_n = mesh_tp(mesh)
    if tp_n > 1 and kernel_backend == "pallas":
        raise ValueError(
            "tensor parallelism shards each slot's W across the tp axis; "
            "the fused pallas flag kernels compute whole slots — use "
            "kernel_backend='xla' with --tp"
        )
    split = bool(getattr(prog, "backward_split", False))
    if split and kernel_backend == "pallas":
        raise ValueError(
            "backward_split needs the XLA per-slot backward (the fused "
            "pallas flag kernel computes dgrad+wgrad in one unit and has "
            "no split halves); use kernel_backend='xla'"
        )
    act = spec.act
    if act != "relu" and kernel_backend == "pallas":
        raise ValueError(
            f"the fused pallas flag kernels implement the relu family only; "
            f"use kernel_backend='xla' for act={act!r} models"
        )
    rec = bool(getattr(prog, "recompute", False))
    if rec and kernel_backend == "pallas":
        raise ValueError(
            "recompute re-runs the stage forward through the XLA slot "
            "functions; use kernel_backend='xla' with --recompute"
        )
    dims = slot_shapes(spec, tp_n)
    # this device's slot geometry: at tp == 1 these ARE the global dims
    # (identical trace, byte for byte); at tp > 1 the Megatron shards
    w_dims, b_widths, xs_widths, mask_widths = tp_local_dims(dims, tp_n)
    S_, L = spec.n_stages, len(dims)
    D_in, D_out = dims[0][1], dims[-1][0]
    # the cross-device axes params/grads are sharded over: the reductions
    # behind the clip/grad-norm/param-norm scalars must span them all
    pp_axes = "pp" if tp_n == 1 else ("pp", "tp")
    z1_axes = ("dp", "pp") if tp_n == 1 else ("dp", "pp", "tp")
    W_rel = relay_width(spec)  # ppermute payload / mailbox width (<= D_in)
    M = prog.num_micro_batches
    Kf, Kb = prog.n_fwd_slots, prog.n_bwd_slots
    Ks = prog.n_stash_slots
    Kg = prog.n_gstash_slots  # grad-stash depth (split programs only)
    Kx = prog.n_xin_slots  # input-stash depth (recompute programs only)
    mb_sz = mubatch_size
    B_global = spec.global_batch_size
    training = prog.is_training
    if training and opt is None:
        raise ValueError("training program needs an optimizer")
    if (with_grad_norm or with_step_stats or with_digests) and not training:
        raise ValueError(
            "with_grad_norm/with_step_stats/with_digests apply to training "
            "programs only"
        )
    if with_step_stats:
        with_grad_norm = True  # step stats carry the grad norm per step
    P_ = mesh.shape["pp"]  # devices on the pp axis
    V = prog.num_chunks  # virtual stages per device
    assert prog.num_stages == P_, "program/mesh device-count mismatch"
    assert S_ == P_ * V, "model stages must equal devices x virtual chunks"
    dp_n = mesh.shape["dp"]
    # gradient-sync plan: None = legacy anchor collective; a BucketPlan =
    # per-bucket collectives (derived deterministically from spec + knob,
    # so the session's audit contract rebuilds the identical plan)
    if grad_bucket_bytes and training:
        from shallowspeed_tpu.parallel import gradsync

        sync_plan = gradsync.plan_buckets(
            spec, dp_n, P_, grad_bucket_bytes, zero=zero, tp=tp_n
        )
    else:
        sync_plan = None
    if zero >= 2 and with_digests:
        raise ValueError(
            "with_digests reads the zero1 flat-chunk segment map; the "
            "block-cyclic shard layout of zero>=2 has no flat chunk — "
            "run digests at --zero 1 or below"
        )
    if zero >= 1:
        if not training:
            if zero1:
                raise ValueError("zero1 applies to training programs only")
            raise ValueError(f"zero={zero} applies to training programs only")
        from shallowspeed_tpu.optimizer import is_stateless

        z1_stateful = not is_stateless(opt)
        if zero1:
            z1_flat, z1_csz = zero1_flat_len(spec, mesh)
            if z1_stateful:
                _zero1_check_state(opt, z1_csz)
        else:
            zb_slots, zb_csz = zero_block_slots(
                spec, mesh.shape["pp"], mesh.shape["dp"], tp_n
            )
            if z1_stateful:
                _zero1_check_state(opt, zb_csz)
        if z1_stateful:
            z1_layout = opt.state_layout()

    # ZeRO-2/3 persistent gradient shard: the anchor zero-2 program and
    # every zero-3 program accumulate the dp-summed gradient as this
    # rank's (csz3,) block-cyclic shard, reduce-scattered per tick
    # (canonical ZeRO-2 ordering: the shard sums microbatch-outer). A
    # bucketed zero-2 plan keeps the full-slab accumulators and the
    # byte-bucketed tail reduce-scatter instead — the overlap trade,
    # which also stays bitwise equal to zero-1 at any microbatch count
    # (the sharded accumulator's reassociated (dp x microbatch) sum is
    # bitwise only at mubatches=1; see docs/performance.md).
    shard_grads = zero == 3 or (zero == 2 and sync_plan is None)

    if with_digests:
        # the digest-grid builders (see the docstring): per-slot columns of
        # per-chunk reductions, scattered at this device's pp row block and
        # psum'd over the axes the params are sharded across, so EVERY
        # device returns the same (S, L) matrix. uint32 checksums wrap mod
        # 2^32 under psum — the same wrap the host reference
        # (utils.block_checksum) computes, so stacked == logical exactly.
        def _digest_scatter(col_fn, slot_vals, dtype, axes):
            grid = jnp.zeros((S_, L), dtype)
            r0 = lax.axis_index("pp") * V
            for sl, a in enumerate(slot_vals):
                col = col_fn(a.astype(jnp.float32))
                grid = lax.dynamic_update_slice(
                    grid, col.reshape(V, 1).astype(dtype), (r0, sl)
                )
            return lax.psum(grid, axes)

        def _crc_col(a32):
            return jnp.sum(
                lax.bitcast_convert_type(a32, jnp.uint32).reshape(V, -1),
                axis=1,
                dtype=jnp.uint32,
            )

        def _sq_col(a32):
            return jnp.sum((a32 * a32).reshape(V, -1), axis=1)

        def _digest_grids(new_p, gsq_w, gsq_b):
            """The step's digest dict from the post-update local params +
            the pre-computed post-sync grad squared-sum grids."""
            return {
                "crc_w": _digest_scatter(
                    _crc_col, new_p["W"], jnp.uint32, pp_axes
                ),
                "crc_b": _digest_scatter(
                    _crc_col, new_p["b"], jnp.uint32, pp_axes
                ),
                "pnorm_w": jnp.sqrt(
                    _digest_scatter(_sq_col, new_p["W"], jnp.float32, pp_axes)
                ),
                "pnorm_b": jnp.sqrt(
                    _digest_scatter(_sq_col, new_p["b"], jnp.float32, pp_axes)
                ),
                "gnorm_w": jnp.sqrt(gsq_w),
                "gnorm_b": jnp.sqrt(gsq_b),
            }

        if zero1:
            # under ZeRO-1 the post-sync gradient lives as this replica's
            # flat (csz,) chunk, so the per-(chunk, slot) squared sums come
            # from a STATIC segment-id map over the padded flat layout
            # (W slots then b slots, chunk-major inside each slot; padding
            # lands in a trash segment) — sliced at this replica's offset
            # and segment-summed, then scattered + psum'd like the rest
            _seg_np = np.concatenate(
                [
                    np.repeat(np.arange(sl * V, (sl + 1) * V), o * i)
                    for sl, (o, i) in enumerate(w_dims)
                ]
                + [
                    np.repeat(np.arange((L + sl) * V, (L + sl + 1) * V), w)
                    for sl, w in enumerate(b_widths)
                ]
            )
            _pad_n = z1_csz * mesh.shape["dp"] - z1_flat
            z1_seg_ids = jnp.asarray(
                np.concatenate([_seg_np, np.full(_pad_n, 2 * L * V)]),
                jnp.int32,
            )

    # tick tables as device constants, scanned over their leading (T) axis
    tab_dict = dict(
        op=prog.op,
        mb=prog.mb,
        rf=prog.read_fwd_slot,
        rb=prog.read_bwd_slot,
        inf=prog.in_fwd_slot,
        inb=prog.in_bwd_slot,
        sf=prog.send_fwd,
        sb=prog.send_bwd,
        sw=prog.stash_write,
        sr=prog.stash_read,
        ck=prog.chunk,
        li=prog.load_in,
        ih=prog.is_head,
    )
    if split:
        # split programs route three extra slot tables: the activation-
        # stash peek (B-input) and the grad-stash write/read pair
        tab_dict.update(
            sp=prog.stash_peek, gw=prog.gstash_write, gr=prog.gstash_read
        )
    if rec:
        # recompute programs route the input-stash write/read pair (the
        # forward stores its stage input; the recompute frees it)
        tab_dict.update(xw=prog.xin_write, xr=prog.xin_read)
    tabs = jax.tree.map(jnp.asarray, tab_dict)
    # ring shifts: with virtual chunks the device-(P-1) -> device-0 wrap IS a
    # stage boundary (chunk c on the last device feeds chunk c+1 on the
    # first); without chunks nothing ever sends on the wrap link and its zero
    # payload lands in the receiver's trash slot
    fwd_perm = [(d, (d + 1) % P_) for d in range(P_)]
    bwd_perm = [(d, (d - 1) % P_) for d in range(P_)]

    def per_device(stacked, flags, opt_state, x, y):
        # local views: stage axis is sharded to V rows per device on pp
        # (device-major interleaved order, so row v IS virtual chunk v)
        if zero == 3:
            # ZeRO-3: params at rest are this rank's block-cyclic shard;
            # tick branches gather the active chunk's segments on demand
            pshard = stacked["P"][0]  # (csz3,)
            WsV = bsV = None
        else:
            WsV = stacked["W"]  # per slot (V, out_l, in_l)
            bsV = stacked["b"]
        activeV = flags["active"]  # (V, L)
        reluV = flags["relu"]
        residualV = flags["residual"]  # (V, L); all-False for relu specs
        head_maskV = flags["head_mask"]  # (V, D_out)
        stage = lax.axis_index("pp")
        tp_idx = lax.axis_index("tp") if tp_n > 1 else 0

        def pick(a, v):
            """Select the active virtual chunk's row (static for V == 1)."""
            if V == 1:
                return a[0]
            return lax.dynamic_index_in_dim(a, v, 0, keepdims=False)

        x = x.reshape(M, mb_sz, D_in)  # local dp shard, padded to D_in
        y = y.reshape(M, mb_sz, D_out) if y is not None else None

        carry = dict(
            fwd_mail=jnp.zeros((Kf + 1, mb_sz, W_rel), jnp.float32),
            bwd_mail=jnp.zeros((Kb + 1, mb_sz, W_rel), jnp.float32),
        )
        if training:
            # residual stashes (lowering-assigned slots, +1 trash), grad
            # accumulators, head-logit stash and the loss tally only exist in
            # training programs — inference never runs a backward, so it
            # carries only its predictions
            # the mask stash holds relu bitmasks (bool) for the relu family
            # and gelu derivative VALUES (f32) for the gelu family — same
            # slot discipline, family-appropriate dtype
            mask_dtype = jnp.bool_ if act == "relu" else jnp.float32
            carry.update(
                xs=tuple(
                    jnp.zeros((Ks + 1, mb_sz, w), jnp.float32)
                    for w in xs_widths
                ),
                masks=tuple(
                    jnp.zeros((Ks + 1, mb_sz, w), mask_dtype)
                    for w in mask_widths
                ),
                z=jnp.zeros((Ks + 1, mb_sz, D_out), jnp.float32),
                loss=jnp.zeros((), jnp.float32),
            )
            if shard_grads:
                # ZeRO-2 (anchor) and ZeRO-3 accumulate the dp-summed
                # gradient directly as this rank's persistent (csz3,)
                # shard — reduce-scattered per tick, never as full
                # (V, o, i) slabs: the stage's gradient-residency claim
                carry.update(gz=jnp.zeros((zb_csz,), jnp.float32))
            else:
                carry.update(
                    gW=tuple(
                        jnp.zeros((V, o, i), jnp.float32) for o, i in w_dims
                    ),
                    gb=tuple(jnp.zeros((V, w), jnp.float32) for w in b_widths),
                )
            if split:
                # grad stash: per-slot effective output-grads, held from
                # each B-input tick to its deferred B-weight tick (slots
                # assigned by the lowering, +1 trash — sized exactly like
                # the activation stash, because it IS the same discipline;
                # widths match the masks': the g_eff of a slot lives in
                # the same representation as its relu mask)
                carry.update(
                    gstash=tuple(
                        jnp.zeros((Kg + 1, mb_sz, w), jnp.float32)
                        for w in mask_widths
                    )
                )
            if rec:
                # recompute input stash: the stage input each forward tick
                # parks (slots assigned by the lowering, +1 trash; the
                # global stage 0 reloads from HBM instead and never claims
                # one). Freed at the recompute tick — the short lifetime
                # analysis/stash.py proves
                carry.update(
                    xin=jnp.zeros((Kx + 1, mb_sz, D_in), jnp.float32)
                )
        else:
            carry.update(preds=jnp.zeros((M + 1, mb_sz, D_out), jnp.float32))
        zero_fwd = jnp.zeros((mb_sz, W_rel), jnp.float32)
        zero_bwd = jnp.zeros((mb_sz, W_rel), jnp.float32)

        def tick(carry, row):
            opv = row["op"][stage]
            mb_i = row["mb"][stage]  # M = trash
            mb_r = jnp.minimum(mb_i, M - 1)  # clamped read index
            v = row["ck"][stage]  # active virtual chunk (0 when V == 1)
            load_in = row["li"][stage] == 1  # compute is the global stage 0 fwd
            is_head = row["ih"][stage] == 1  # compute is the global last stage

            def chunk_flags():
                """The active chunk's flag rows — no weights, so branches
                that never touch weights (split B-weight) emit no ZeRO-3
                gathers."""
                return (
                    pick(activeV, v),
                    pick(reluV, v),
                    pick(residualV, v),
                    pick(head_maskV, v),
                )

            def chunk_weights():
                """The active chunk's weights: resident-row picks at
                stages 0-2; just-in-time per-slot all-gathers of this
                chunk's shard segments at ZeRO-3 (gathered copies die with
                the branch — peak live params is one chunk, not the
                model)."""
                if zero != 3:
                    return [pick(w, v) for w in WsV], [pick(b, v) for b in bsV]
                gathered = []
                for s in zb_slots:
                    if V == 1:
                        seg = lax.slice_in_dim(pshard, s.off, s.off + s.k)
                    else:
                        seg = lax.dynamic_slice(
                            pshard, (s.off + v * s.k,), (s.k,)
                        )
                    full = lax.all_gather(seg, "dp", axis=0, tiled=True)
                    gathered.append(full[: s.sz].reshape(s.shape))
                return gathered[:L], gathered[L:]

            def chunk_params():
                Ws, bs = chunk_weights()
                return (Ws, bs) + chunk_flags()

            def z3_scatter_grads(c, gW_d, gb_d):
                """ZeRO-2/3 per-tick gradient sync: reduce-scatter each
                slot's chunk-row gradient over dp and accumulate the (k,)
                shard at this chunk's segment of the persistent gz."""
                gz = c["gz"]
                for s, g in zip(zb_slots, list(gW_d) + list(gb_d)):
                    vec = jnp.pad(g.reshape(-1), (0, dp_n * s.k - s.sz))
                    sh = lax.psum_scatter(
                        vec, "dp", scatter_dimension=0, tiled=True
                    )
                    if V == 1:
                        gz = gz.at[s.off : s.off + s.k].add(sh)
                    else:
                        a = s.off + v * s.k
                        seg = lax.dynamic_slice(gz, (a,), (s.k,))
                        gz = lax.dynamic_update_slice(gz, seg + sh, (a,))
                c = dict(c)
                c["gz"] = gz
                return c

            def noop(c):
                return c, zero_fwd, zero_bwd

            def run_stage_fwd(Ws, bs, active, relu, residual, x_in):
                """The ONE stage-forward call both the forward tick and the
                recompute tick make — character-identical expressions from
                a bitwise-identical input are the recompute parity
                contract."""
                if tp_n > 1:
                    return _stage_fwd_tp(
                        Ws, bs, active, relu, dims, x_in, precision,
                        tp_idx, tp_n, act=act, residual=residual,
                    )
                return _stage_fwd(
                    Ws, bs, active, relu, dims, x_in, precision,
                    kernel_backend, act=act, residual=residual,
                )

            def forward(c):
                Ws, bs, active, relu, residual, head_mask = chunk_params()
                # non-input stages receive a W_rel-wide relay; pad it up to
                # D_in so both branches of the where agree (exact: relayed
                # activations are zero beyond their true boundary width)
                x_in = jnp.where(
                    load_in, x[mb_r], _fit(c["fwd_mail"][row["rf"][stage]], D_in)
                )
                out, xs_l, masks_l = run_stage_fwd(
                    Ws, bs, active, relu, residual, x_in
                )
                c = dict(c)
                p = ops.softmax(out, valid_mask=head_mask[None, :])
                if training:
                    if rec:
                        # recompute program: park only the stage INPUT (the
                        # residuals are re-derived at the recompute tick);
                        # the global stage 0 reloads from HBM, its xw is
                        # the trash slot
                        xw = row["xw"][stage]
                        c["xin"] = c["xin"].at[xw].set(x_in)
                    else:
                        sw = row["sw"][stage]  # lowering-assigned stash slot
                        c["xs"] = tuple(
                            buf.at[sw].set(val)
                            for buf, val in zip(c["xs"], xs_l)
                        )
                        c["masks"] = tuple(
                            buf.at[sw].set(val)
                            for buf, val in zip(c["masks"], masks_l)
                        )
                        c["z"] = c["z"].at[sw].set(out)
                    mb_loss = ops.mse_loss(p, y[mb_r], B_global)
                    c["loss"] = c["loss"] + jnp.where(is_head, mb_loss, 0.0)
                else:
                    c["preds"] = c["preds"].at[mb_i].set(jnp.where(is_head, p, 0.0))
                payload = jnp.where(row["sf"][stage] == 1, _fit(out, W_rel), 0.0)
                return c, payload, zero_bwd

            def recompute(c):
                # OP_RECOMPUTE: re-run the stage forward from the parked
                # input and stash the residuals the imminent backward
                # consumes. Same input bits + the same run_stage_fwd
                # expressions = bitwise-identical xs/masks/z to what the
                # stashed twin's forward tick stored. No loss accumulation
                # (the forward tick already tallied it), no sends.
                Ws, bs, active, relu, residual, head_mask = chunk_params()
                x_in = jnp.where(
                    load_in, x[mb_r], _fit(c["xin"][row["xr"][stage]], D_in)
                )
                out, xs_l, masks_l = run_stage_fwd(
                    Ws, bs, active, relu, residual, x_in
                )
                c = dict(c)
                sw = row["sw"][stage]
                c["xs"] = tuple(
                    buf.at[sw].set(val) for buf, val in zip(c["xs"], xs_l)
                )
                c["masks"] = tuple(
                    buf.at[sw].set(val) for buf, val in zip(c["masks"], masks_l)
                )
                c["z"] = c["z"].at[sw].set(out)
                return c, zero_fwd, zero_bwd

            def backward(c):
                Ws, bs, active, relu, residual, head_mask = chunk_params()
                # lowering guarantees every training backward has a real
                # stash slot in [0, Ks) (replay-asserted), so no clamp needed
                sr = row["sr"][stage]
                g0 = ops.softmax_mse_head_grad(
                    c["z"][sr], y[mb_r], B_global, valid_mask=head_mask[None, :]
                )
                # head grad is D_out wide, relayed grads W_rel wide; fit both
                # to the wider so the where agrees (padding is exact zeros)
                Wb = max(D_out, W_rel)
                g_in = jnp.where(
                    is_head, _fit(g0, Wb), _fit(c["bwd_mail"][row["rb"][stage]], Wb)
                )
                xs_r = tuple(buf[sr] for buf in c["xs"])
                masks_r = tuple(buf[sr] for buf in c["masks"])
                if tp_n > 1:
                    dx, gW_d, gb_d = _stage_bwd_tp(
                        Ws, active, relu, dims, xs_r, masks_r, g_in,
                        precision, tp_idx, tp_n, act=act, residual=residual,
                    )
                else:
                    dx, gW_d, gb_d = _stage_bwd(
                        Ws, active, relu, dims, xs_r, masks_r, g_in,
                        precision, kernel_backend, act=act, residual=residual,
                    )
                c = dict(c)
                if shard_grads:
                    c = z3_scatter_grads(c, gW_d, gb_d)
                elif V == 1:
                    c["gW"] = tuple(a.at[0].add(d) for a, d in zip(c["gW"], gW_d))
                    c["gb"] = tuple(a.at[0].add(d) for a, d in zip(c["gb"], gb_d))
                else:
                    c["gW"] = tuple(a.at[v].add(d) for a, d in zip(c["gW"], gW_d))
                    c["gb"] = tuple(a.at[v].add(d) for a, d in zip(c["gb"], gb_d))
                payload = jnp.where(row["sb"][stage] == 1, _fit(dx, W_rel), 0.0)
                return c, zero_fwd, payload

            def backward_input(c):
                # split B-input: the combined backward's dgrad chain at the
                # SAME tick — PEEKS the activation stash (masks + logits;
                # the B-weight frees it later) and stashes the per-slot
                # effective output-grads for the deferred wgrad
                Ws, bs, active, relu, residual, head_mask = chunk_params()
                sp = row["sp"][stage]
                g0 = ops.softmax_mse_head_grad(
                    c["z"][sp], y[mb_r], B_global, valid_mask=head_mask[None, :]
                )
                Wb = max(D_out, W_rel)
                g_in = jnp.where(
                    is_head, _fit(g0, Wb), _fit(c["bwd_mail"][row["rb"][stage]], Wb)
                )
                masks_r = tuple(buf[sp] for buf in c["masks"])
                if tp_n > 1:
                    dx, g_effs = _stage_bwd_input_tp(
                        Ws, active, relu, dims, masks_r, g_in, precision,
                        tp_idx, tp_n, act=act, residual=residual,
                    )
                else:
                    dx, g_effs = _stage_bwd_input(
                        Ws, active, relu, dims, masks_r, g_in, precision,
                        act=act, residual=residual,
                    )
                c = dict(c)
                gw = row["gw"][stage]
                c["gstash"] = tuple(
                    buf.at[gw].set(val) for buf, val in zip(c["gstash"], g_effs)
                )
                payload = jnp.where(row["sb"][stage] == 1, _fit(dx, W_rel), 0.0)
                return c, zero_fwd, payload

            def backward_weight(c):
                # split B-weight: wgrads from the two stashes, accumulated
                # in lowering-enforced B-input order (bit-identical fp sums
                # vs the combined schedule); frees both stash slots by
                # overwrite-on-reuse — no messages in or out. Flags only:
                # wgrad never touches weights, so ZeRO-3 gathers nothing
                active, _, _, _ = chunk_flags()
                sr = row["sr"][stage]
                gr = row["gr"][stage]
                xs_r = tuple(buf[sr] for buf in c["xs"])
                geff_r = tuple(buf[gr] for buf in c["gstash"])
                if tp_n > 1:
                    gW_d, gb_d = _stage_bwd_weight_tp(
                        active, dims, xs_r, geff_r, precision, tp_idx, tp_n
                    )
                else:
                    gW_d, gb_d = _stage_bwd_weight(
                        active, dims, xs_r, geff_r, precision
                    )
                c = dict(c)
                if shard_grads:
                    c = z3_scatter_grads(c, gW_d, gb_d)
                elif V == 1:
                    c["gW"] = tuple(a.at[0].add(d) for a, d in zip(c["gW"], gW_d))
                    c["gb"] = tuple(a.at[0].add(d) for a, d in zip(c["gb"], gb_d))
                else:
                    c["gW"] = tuple(a.at[v].add(d) for a, d in zip(c["gW"], gW_d))
                    c["gb"] = tuple(a.at[v].add(d) for a, d in zip(c["gb"], gb_d))
                return c, zero_fwd, zero_bwd

            # branch order is the op-code encoding: OP_NOOP=0, OP_FWD=1,
            # OP_BWD=2 (B-input when split), OP_BWD_W=3, OP_RECOMPUTE=4
            assert (OP_FWD, OP_BWD, OP_BWD_W, OP_RECOMPUTE) == (1, 2, 3, 4)
            if training and split:
                branches = [noop, forward, backward_input, backward_weight]
            else:
                branches = [noop, forward] + ([backward] if training else [noop])
            if training and rec:
                # recompute programs may not use OP_BWD_W without split, but
                # the switch is indexed by op code, so pad to position 4
                while len(branches) < OP_RECOMPUTE:
                    branches.append(noop)
                branches.append(recompute)
            carry, fwd_out, bwd_out = lax.switch(opv, branches, carry)

            # uniform collectives outside the switch: relay payloads
            incoming_f = lax.ppermute(fwd_out, "pp", fwd_perm)
            incoming_b = lax.ppermute(bwd_out, "pp", bwd_perm)
            carry["fwd_mail"] = carry["fwd_mail"].at[row["inf"][stage]].set(incoming_f)
            carry["bwd_mail"] = carry["bwd_mail"].at[row["inb"][stage]].set(incoming_b)
            return carry, None

        # tick_unroll amortizes the scan's per-tick loop overhead (each tick
        # body is one small stage compute + two ppermutes); numerics identical
        carry, _ = lax.scan(tick, carry, tabs, unroll=tick_unroll)

        if not training:
            preds = carry["preds"][:M].reshape(M * mb_sz, D_out)
            # only head-stage ticks ever wrote predictions (zeros elsewhere);
            # broadcast them over pp
            return lax.psum(preds, "pp")

        # loss was only accumulated on head-stage ticks (zero elsewhere)
        loss = lax.psum(carry["loss"], "dp")
        loss = lax.pmax(loss, "pp")  # replicate scalar across devices

        if zero >= 2:
            # ZeRO-2/3 tail: the dp-summed gradient lives as this rank's
            # block-cyclic (csz3,) shard. The anchor zero-2 program and
            # every zero-3 program accumulated it per tick (shard_grads);
            # a bucketed zero-2 plan reduce-scatters its full-slab
            # accumulators HERE, one byte-bucket at a time (elementwise
            # over the same (dp, chunk) column deal, so the bucketed
            # shard is zero-1's update input, bitwise).
            if shard_grads:
                gsh = carry["gz"]
            else:
                mats = [
                    _zb_scatter_rows(g.reshape(s.rows, s.sz), dp_n, s.k)
                    for s, g in zip(
                        zb_slots, list(carry["gW"]) + list(carry["gb"])
                    )
                ]
                # byte-bucketed: one collective per (slot, column
                # range) bucket in backward emission order; the
                # reassembled shard is the anchor's column deal, bitwise
                pieces = [[] for _ in zb_slots]
                for si, a, b in sync_plan.buckets:
                    pieces[si].append(
                        (
                            a,
                            lax.psum_scatter(
                                mats[si][:, a:b],
                                "dp",
                                scatter_dimension=0,
                                tiled=False,
                            ),
                        )
                    )
                gsh = jnp.concatenate(
                    [
                        p
                        for ps in pieces
                        for _, p in sorted(ps, key=lambda t: t[0])
                    ]
                )
            if with_grad_norm:
                # shards partition the dp-summed gradient across every
                # sharded axis; per-slot padding is exactly zero
                gnorm = jnp.sqrt(lax.psum(jnp.sum(gsh * gsh), z1_axes))
            if clip_norm is not None:
                from shallowspeed_tpu.optimizer import clip_tree

                gsh = clip_tree(
                    gsh, clip_norm, lambda sq: lax.psum(sq, z1_axes)
                )
            if zero == 3:
                pch = pshard
            else:
                # this rank's param chunk: the same per-slot column deal,
                # sliced at the dp index on the deal VIEW — shard-sized
                # temporaries, no transposed slab
                d0 = lax.axis_index("dp")
                pch = jnp.concatenate(
                    [
                        lax.dynamic_slice(
                            _zb_deal_view(
                                p.reshape(s.rows, s.sz), dp_n, s.k
                            ),
                            (0, d0, 0),
                            (s.rows, 1, s.k),
                        ).reshape(-1)
                        for s, p in zip(
                            zb_slots, list(stacked["W"]) + list(stacked["b"])
                        )
                    ]
                )
            if z1_stateful:
                from shallowspeed_tpu.optimizer import join_state, split_state

                chunk_state = join_state(
                    opt,
                    {k: opt_state[k][0] for k, kd in z1_layout.items() if kd == "params"},
                    {k: opt_state[k] for k, kd in z1_layout.items() if kd == "scalar"},
                )
                new_ch, new_state = opt.apply(pch, gsh, chunk_state)
                nparts, nscalars = split_state(opt, new_state)
                opt_state = {k: v[None] for k, v in nparts.items()}
                opt_state.update(nscalars)
            else:
                new_ch, _ = opt.apply(pch, gsh, ())
            if zero == 3:
                # params stay at rest in the shard layout; the next step's
                # tick branches gather from the updated chunk
                new_stacked = {"P": new_ch[None]}
            else:
                # per-slot all-gather of the updated chunks rebuilds the
                # resident params: gathering on axis 1 of the (rows, 1, k)
                # segment lands ranks straight into the deal view's
                # (rows, dp, k) layout, so the inverse is a reshape +
                # padding slice — no transposed slab
                outW, outb = [], []
                for s in zb_slots:
                    seg = new_ch[s.off : s.off + s.rows * s.k].reshape(
                        s.rows, 1, s.k
                    )
                    mat = lax.all_gather(seg, "dp", axis=1, tiled=True)
                    full = mat.reshape(s.rows, dp_n * s.k)[:, : s.sz]
                    (outW if s.kind == "W" else outb).append(
                        full.reshape((s.rows,) + s.shape)
                    )
                new_stacked = {"W": tuple(outW), "b": tuple(outb)}
            outs = (new_stacked, opt_state, loss)
            if with_grad_norm:
                outs += (gnorm,)
            if with_step_stats:
                if zero == 3:
                    # chunk shards partition the params exactly (padding
                    # is exactly zero), so the shard norm IS the logical
                    # norm after the cross-axis psum
                    outs += (
                        jnp.sqrt(
                            lax.psum(jnp.sum(new_ch * new_ch), z1_axes)
                        ),
                    )
                else:
                    from shallowspeed_tpu.optimizer import (
                        global_norm as gnorm_of,
                    )

                    outs += (
                        gnorm_of(
                            new_stacked, lambda sq: lax.psum(sq, pp_axes)
                        ),
                    )
            return outs

        if zero1:
            # ZeRO-1: reduce_scatter the flattened gradient over dp, update
            # this replica's param chunk with its state shard, all_gather
            flat, csz = z1_flat, z1_csz
            pad = csz * dp_n - flat
            gvec = jnp.concatenate(
                [g.reshape(-1) for g in carry["gW"]]
                + [g.reshape(-1) for g in carry["gb"]]
            )
            # the gradient sync: one flat reduce-scatter at the anchor, or
            # one per byte-bucket (column ranges of the (dp, chunk) view —
            # the concatenated outputs ARE the anchor chunk, bitwise)
            gpad = jnp.pad(gvec, (0, pad))
            if sync_plan is None:
                gsh = lax.psum_scatter(
                    gpad, "dp", scatter_dimension=0, tiled=True
                )
            else:
                from shallowspeed_tpu.parallel import gradsync

                gsh = gradsync.psum_scatter_bucketed(gpad, sync_plan)
            if with_grad_norm:
                # chunks partition the dp-summed gradient across every
                # sharded axis, so the pre-clip global norm is one
                # cross-axis reduction
                gnorm = jnp.sqrt(lax.psum(jnp.sum(gsh * gsh), z1_axes))
            if with_digests:
                # per-(chunk, slot) grad squared sums from this replica's
                # flat chunk: static segment ids sliced at the chunk
                # offset, one psum over EVERY sharded axis (dp chunks +
                # pp rows + tp shards are all disjoint)
                ids = lax.dynamic_slice(
                    z1_seg_ids, (lax.axis_index("dp") * csz,), (csz,)
                )
                seg = jax.ops.segment_sum(
                    gsh * gsh, ids, num_segments=2 * L * V + 1
                )[: 2 * L * V]
                r0 = lax.axis_index("pp") * V
                dgsq_w = lax.psum(
                    lax.dynamic_update_slice(
                        jnp.zeros((S_, L), jnp.float32),
                        seg[: L * V].reshape(L, V).T,
                        (r0, 0),
                    ),
                    z1_axes,
                )
                dgsq_b = lax.psum(
                    lax.dynamic_update_slice(
                        jnp.zeros((S_, L), jnp.float32),
                        seg[L * V :].reshape(L, V).T,
                        (r0, 0),
                    ),
                    z1_axes,
                )
            if clip_norm is not None:
                from shallowspeed_tpu.optimizer import clip_tree

                # chunks partition the full summed gradient across the
                # sharded axes (dp, pp[, tp])
                gsh = clip_tree(
                    gsh, clip_norm, lambda sq: lax.psum(sq, z1_axes)
                )
            pvec = jnp.concatenate(
                [w.reshape(-1) for w in stacked["W"]]
                + [b.reshape(-1) for b in stacked["b"]]
            )
            pvec = jnp.pad(pvec, (0, pad))
            i0 = lax.axis_index("dp") * csz
            pch = lax.dynamic_slice(pvec, (i0,), (csz,))
            if z1_stateful:
                from shallowspeed_tpu.optimizer import join_state, split_state

                # per-device views: 'params' parts are (1, csz) blocks,
                # scalars are replicated 0-d
                chunk_state = join_state(
                    opt,
                    {k: opt_state[k][0] for k, kd in z1_layout.items() if kd == "params"},
                    {k: opt_state[k] for k, kd in z1_layout.items() if kd == "scalar"},
                )
                new_ch, new_state = opt.apply(pch, gsh, chunk_state)
                nparts, nscalars = split_state(opt, new_state)
                opt_state = {k: v[None] for k, v in nparts.items()}
                opt_state.update(nscalars)
            else:
                new_ch, _ = opt.apply(pch, gsh, ())
            new_vec = lax.all_gather(new_ch, "dp", axis=0, tiled=True)[:flat]
            outW, outb, off = [], [], 0
            for o, i in w_dims:  # this device's LOCAL slot shapes
                n = V * o * i
                outW.append(new_vec[off : off + n].reshape(V, o, i))
                off += n
            for w in b_widths:
                n = V * w
                outb.append(new_vec[off : off + n].reshape(V, w))
                off += n
            new_stacked = {"W": tuple(outW), "b": tuple(outb)}
            outs = (new_stacked, opt_state, loss)
            if with_grad_norm:
                outs += (gnorm,)
            if with_step_stats:
                from shallowspeed_tpu.optimizer import global_norm as gnorm_of

                # post-update param norm: padded entries are exactly zero,
                # so the pp-psum'd stacked norm IS the logical norm
                outs += (gnorm_of(new_stacked, lambda sq: lax.psum(sq, pp_axes)),)
            if with_digests:
                outs += (_digest_grids(new_stacked, dgsq_w, dgsq_b),)
            return outs

        # the BackwardGradAllReduce anchor, in one of two bitwise-identical
        # forms (reference pipe.py:302-327): legacy — one SUM-psum of the
        # whole gradient pytree over dp per batch — or bucketed — one psum
        # per backward-ordered byte-bucket, so XLA can overlap each
        # bucket's all-reduce with the rest of the tail. The clip-norm /
        # grad-norm consumers below always read the POST-SYNC tree.
        if sync_plan is None:
            gW = lax.psum(carry["gW"], "dp")
            gb = lax.psum(carry["gb"], "dp")
            grads = {"W": gW, "b": gb}  # (V, ...) leaves, mirroring the shards
        else:
            from shallowspeed_tpu.parallel import gradsync

            grads = gradsync.psum_bucketed(
                {"W": carry["gW"], "b": carry["gb"]}, sync_plan
            )
        if with_grad_norm:
            from shallowspeed_tpu.optimizer import global_norm

            # each pp device holds its stages' full (dp-summed) gradient;
            # padded entries are exactly zero so this IS the logical norm
            gnorm = global_norm(grads, lambda sq: lax.psum(sq, pp_axes))
        if with_digests:
            # post-sync PRE-clip per-block grad squared sums (the clip
            # below reassigns ``grads``)
            dgsq_w = _digest_scatter(_sq_col, grads["W"], jnp.float32, pp_axes)
            dgsq_b = _digest_scatter(_sq_col, grads["b"], jnp.float32, pp_axes)
        if clip_norm is not None:
            from shallowspeed_tpu.optimizer import clip_tree

            # each pp device holds its stages' full (dp-summed) gradient;
            # the global norm needs the cross-stage total
            grads = clip_tree(grads, clip_norm, lambda sq: lax.psum(sq, pp_axes))
        local = {"W": stacked["W"], "b": stacked["b"]}
        new_local, opt_state = opt.apply(local, grads, opt_state)
        outs = (new_local, opt_state, loss)
        if with_grad_norm:
            outs += (gnorm,)
        if with_step_stats:
            from shallowspeed_tpu.optimizer import global_norm as gnorm_of

            outs += (gnorm_of(new_local, lambda sq: lax.psum(sq, pp_axes)),)
        if with_digests:
            outs += (_digest_grids(new_local, dgsq_w, dgsq_b),)
        return outs

    pp = P("pp")
    dp_spec = P("dp")
    flags_specs = {"active": pp, "relu": pp, "residual": pp, "head_mask": pp}
    if zero == 3:
        # ZeRO-3 params at rest: one (pp*tp, dp*csz3) block-cyclic array,
        # rows per (pp, tp) device, column-chunk per dp rank — the same
        # spec the sharded optimizer state rides
        stacked_specs = {"P": zero1_part_spec(tp_n)}
    else:
        stacked_specs = stacked_param_specs(tp_n, L)

    if training:
        if zero >= 1:
            # ZeRO-1/2/3 state: one (pp[*tp], dp*chunk) array per 'params'
            # part (row per (pp, tp) device, column-chunk per dp replica)
            # + replicated scalars; () for stateless optimizers
            state_specs = (
                {
                    k: (zero1_part_spec(tp_n) if kd == "params" else P())
                    for k, kd in z1_layout.items()
                }
                if z1_stateful
                else ()
            )
        elif tp_n == 1:
            # optimizer-state specs mirror the state's pytree: stage-axis
            # sharded like the params it tracks (SGD's state is the empty
            # tuple)
            stacked_struct = {
                "W": tuple(
                    jax.ShapeDtypeStruct((S_, o, i), jnp.float32) for o, i in dims
                ),
                "b": tuple(
                    jax.ShapeDtypeStruct((S_, o), jnp.float32) for o, _ in dims
                ),
            }
            state_struct = jax.eval_shape(opt.init, stacked_struct)
            # stage-stacked state leaves (leading axis S, like the params
            # they track) shard over pp; anything else (scalar step counts
            # etc.) is replicated
            state_specs = jax.tree.map(
                lambda leaf: pp if leaf.ndim > 0 and leaf.shape[0] == S_ else P(),
                state_struct,
            )
        else:
            # tp > 1: state parts must mirror the params EXACTLY (the
            # state_layout protocol — same requirement zero1 enforces), so
            # each part takes the params' per-slot column/row shards and
            # scalars replicate
            from shallowspeed_tpu.optimizer import join_state, split_state

            stacked_struct = {
                "W": tuple(
                    jax.ShapeDtypeStruct((S_, o, i), jnp.float32) for o, i in dims
                ),
                "b": tuple(
                    jax.ShapeDtypeStruct((S_, o), jnp.float32) for o, _ in dims
                ),
            }
            state_struct = jax.eval_shape(opt.init, stacked_struct)
            parts, scalars = split_state(opt, state_struct)
            state_specs = join_state(
                opt,
                {k: stacked_specs for k in parts},
                {k: P() for k in scalars},
            )

        out_specs = (stacked_specs, state_specs, P())
        if with_grad_norm:
            out_specs = out_specs + (P(),)  # replicated pre-clip grad norm
        if with_step_stats:
            out_specs = out_specs + (P(),)  # replicated post-update param norm
        if with_digests:
            # the psum'd digest grids are replicated (S, L) matrices
            out_specs = out_specs + (
                {
                    k: P()
                    for k in (
                        "crc_w", "crc_b", "pnorm_w", "pnorm_b",
                        "gnorm_w", "gnorm_b",
                    )
                },
            )
        smapped = shard_map(
            per_device,
            mesh=mesh,
            in_specs=(stacked_specs, flags_specs, state_specs, dp_spec, dp_spec),
            out_specs=out_specs,
            check_vma=False,
        )

        def step_impl(stacked, flags, opt_state, x, y):
            return smapped(stacked, flags, opt_state, _fit(x, D_in), _fit(y, D_out))

        if jit:
            return jax.jit(step_impl, donate_argnums=(0, 2))
        return step_impl

    smapped = shard_map(
        lambda stacked, flags, x: per_device(stacked, flags, (), x, None),
        mesh=mesh,
        in_specs=(stacked_specs, flags_specs, dp_spec),
        out_specs=P("dp"),
        check_vma=False,
    )

    def eval_impl(stacked, flags, x):
        return smapped(stacked, flags, _fit(x, D_in))

    return jax.jit(eval_impl) if jit else eval_impl


def make_pipeline_epoch(
    mesh,
    spec,
    prog,
    mubatch_size,
    opt,
    precision=ops.DEFAULT_PRECISION,
    unroll=1,
    tick_unroll=1,
    zero1=False,
    zero=None,
    clip_norm=None,
    kernel_backend="xla",
    with_grad_norm=False,
    with_step_stats=False,
    with_digests=False,
    grad_bucket_bytes=0,
):
    """Scan the pipeline train step over all batches of an epoch: one XLA
    program per epoch. X: (num_batches, global_batch, in_dim), batch axis
    sharded over dp. ``epoch(stacked, flags, opt_state, X, Y) -> (stacked,
    opt_state, mean_loss)``. ``unroll``/``tick_unroll``: lax.scan unroll
    factors for the batch loop / the per-tick loop (throughput knobs,
    identical numerics); ``zero1`` shards the optimizer update over dp;
    ``clip_norm`` clips the global gradient norm before each update;
    ``kernel_backend`` selects the per-slot compute unit (see
    make_pipeline_step); ``with_grad_norm`` appends a telemetry aux dict
    ``{"grad_norm": mean pre-clip global grad norm}`` as a fourth output;
    ``with_step_stats`` adds per-step ``step_loss``/``step_grad_norm``/
    ``step_param_norm`` vectors to that aux (both mirror
    trainer.make_train_epoch's aux, so TrainingSession records the same
    scalars on every layout); ``with_digests`` adds the per-step stacked
    digest grids under the aux's ``"digests"`` key (each leaf
    ``(num_batches, S, L)`` — see make_pipeline_step's digest contract);
    ``zero`` selects the full dp-axis ZeRO stage {0..3} (supersedes the
    ``zero1`` boolean; see make_pipeline_step — at stage 3 ``stacked`` is
    the ``{"P"}`` shard layout throughout the epoch);
    ``grad_bucket_bytes`` selects the gradient-
    sync mode (0 = anchor collective, >0 = byte-bucketed — see
    make_pipeline_step)."""
    step = make_pipeline_step(
        mesh, spec, prog, mubatch_size, opt, precision, jit=False,
        tick_unroll=tick_unroll, zero1=zero1, zero=zero, clip_norm=clip_norm,
        kernel_backend=kernel_backend, with_grad_norm=with_grad_norm,
        with_step_stats=with_step_stats, with_digests=with_digests,
        grad_bucket_bytes=grad_bucket_bytes,
    )
    return jax.jit(
        _make_pipeline_epoch_core(
            step, unroll, with_grad_norm, with_step_stats, with_digests
        ),
        donate_argnums=(0, 2),
    )


def _make_pipeline_epoch_core(
    step, unroll, with_grad_norm=False, with_step_stats=False,
    with_digests=False,
):
    """The one batch-scan epoch body shared by make_pipeline_epoch and
    make_pipeline_run: ``core(stacked, flags, opt_state, X, Y) ->
    (stacked, opt_state, mean_loss)`` — plus an aux dict when instrumented
    (``grad_norm`` mean under ``with_grad_norm``; stacked per-step
    ``step_loss``/``step_grad_norm``/``step_param_norm`` vectors under
    ``with_step_stats``, as ordinary scan ys). One scan body serves every
    arity: the grad-norm slot always rides the carry (zero when the aux is
    off) and XLA dead-code-eliminates it from the uninstrumented program."""
    track_gn = with_grad_norm or with_step_stats

    def epoch_core(stacked, flags, opt_state, X, Y):
        def body(carry, xy):
            stacked, opt_state, loss_sum, gn_sum = carry
            out = step(stacked, flags, opt_state, xy[0], xy[1])
            stacked, opt_state, loss = out[0], out[1], out[2]
            gn = out[3] if track_gn else jnp.zeros(())
            carry = (stacked, opt_state, loss_sum + loss, gn_sum + gn)
            ys = ()
            if with_step_stats:
                ys += (loss, gn, out[4])
            if with_digests:
                ys += (out[-1],)  # the digest dict rides last (see step)
            return carry, (ys if ys else None)

        (stacked, opt_state, loss_sum, gn_sum), ys = lax.scan(
            body,
            (stacked, opt_state, jnp.zeros(()), jnp.zeros(())),
            (X, Y),
            unroll=unroll,
        )
        nb = X.shape[0]
        if not (with_grad_norm or with_step_stats or with_digests):
            return stacked, opt_state, loss_sum / nb
        aux = {}
        if with_grad_norm:
            aux["grad_norm"] = gn_sum / nb
        if with_step_stats:
            aux["step_loss"], aux["step_grad_norm"], aux["step_param_norm"] = (
                ys[0], ys[1], ys[2]
            )
        if with_digests:
            aux["digests"] = ys[-1]
        return stacked, opt_state, loss_sum / nb, aux

    return epoch_core


def make_pipeline_run(
    mesh,
    spec,
    prog,
    mubatch_size,
    opt,
    precision=ops.DEFAULT_PRECISION,
    unroll=1,
    tick_unroll=1,
    zero1=False,
    zero=None,
    clip_norm=None,
    eval_prog=None,
    eval_mubatch_size=None,
    kernel_backend="xla",
    with_grad_norm=False,
    grad_bucket_bytes=0,
):
    """Epochs-outer scan around the pipeline epoch: the whole multi-epoch run
    as ONE XLA program over the mesh (the pipeline counterpart of
    trainer.make_train_run — zero host round-trips for the full run).

    Without eval: ``run(stacked, flags, opt_state, X, Y, n_epochs) ->
    (stacked, opt_state, losses[n_epochs])``.

    With ``eval_prog`` (an InferenceSchedule TickProgram lowered for the
    padded validation row count): ``run(stacked, flags, opt_state, X, Y,
    vx_padded, vy_labels, n_epochs) -> (stacked, opt_state, losses, accs)``
    where the full-split argmax accuracy is computed on-device after each
    epoch (vy_labels: (n_val,) int labels, unpadded — the static slice
    drops the padded rows).

    ``with_grad_norm``: telemetry aux, mirroring trainer.make_train_run's —
    one EXTRA trailing output, an aux dict whose ``"grad_norm"`` is the
    (n_epochs,) vector of per-epoch mean pre-clip global gradient norms
    (ordinary scan outputs, so the run stays one fused program; this closes
    the mesh-fused-run gap docs/observability.md used to document).

    ``n_epochs`` is static (one compile per value); ``grad_bucket_bytes``
    selects the gradient-sync mode (see make_pipeline_step).
    """
    if zero is not None and int(zero) == 3:
        raise ValueError(
            "the fused multi-epoch run cannot shard params at rest: its "
            "eval step consumes the full stacked layout every epoch — "
            "use --zero 3 without --fused-run (per-epoch dispatch)"
        )
    step = make_pipeline_step(
        mesh, spec, prog, mubatch_size, opt, precision, jit=False,
        tick_unroll=tick_unroll, zero1=zero1, zero=zero, clip_norm=clip_norm,
        kernel_backend=kernel_backend, with_grad_norm=with_grad_norm,
        grad_bucket_bytes=grad_bucket_bytes,
    )
    eval_step = None
    if eval_prog is not None:
        eval_step = make_pipeline_step(
            mesh, spec, eval_prog, eval_mubatch_size, precision=precision,
            jit=False, kernel_backend=kernel_backend,
        )
    out_dim = spec.out_dim
    epoch_core = _make_pipeline_epoch_core(step, unroll, with_grad_norm)

    def run_epoch(stacked, flags, opt_state, X, Y):
        """Uniform (stacked, opt_state, loss, gnorm) view of the epoch core
        (gnorm 0 when the aux is off — dropped again before returning)."""
        if with_grad_norm:
            stacked, opt_state, mean_loss, aux = epoch_core(
                stacked, flags, opt_state, X, Y
            )
            return stacked, opt_state, mean_loss, aux["grad_norm"]
        stacked, opt_state, mean_loss = epoch_core(stacked, flags, opt_state, X, Y)
        return stacked, opt_state, mean_loss, jnp.zeros(())

    if eval_step is None:

        @partial(jax.jit, static_argnums=(5,), donate_argnums=(0, 2))
        def run(stacked, flags, opt_state, X, Y, n_epochs):
            def epoch_body(carry, _):
                stacked, opt_state = carry
                stacked, opt_state, mean_loss, gn = run_epoch(
                    stacked, flags, opt_state, X, Y
                )
                return (stacked, opt_state), (mean_loss, gn)

            (stacked, opt_state), (losses, gns) = lax.scan(
                epoch_body, (stacked, opt_state), None, length=n_epochs
            )
            if with_grad_norm:
                return stacked, opt_state, losses, {"grad_norm": gns}
            return stacked, opt_state, losses

        return run

    @partial(jax.jit, static_argnums=(7,), donate_argnums=(0, 2))
    def run(stacked, flags, opt_state, X, Y, vx_padded, vy_labels, n_epochs):
        n_val = vy_labels.shape[0]

        def epoch_body(carry, _):
            stacked, opt_state = carry
            stacked, opt_state, mean_loss, gn = run_epoch(
                stacked, flags, opt_state, X, Y
            )
            preds = eval_step(stacked, flags, vx_padded)[:n_val, :out_dim]
            acc = jnp.mean((jnp.argmax(preds, axis=1) == vy_labels).astype(jnp.float32))
            return (stacked, opt_state), (mean_loss, acc, gn)

        (stacked, opt_state), (losses, accs, gns) = lax.scan(
            epoch_body, (stacked, opt_state), None, length=n_epochs
        )
        if with_grad_norm:
            return stacked, opt_state, losses, accs, {"grad_norm": gns}
        return stacked, opt_state, losses, accs

    return run
