"""Cost model (analytical FLOPs, cost_analysis cross-check, MFU) and the
run-report CLI: round-trip on a real TrainingSession JSONL, baseline
regression gating, v1-file compatibility, rendering formats.
"""

import json

import numpy as np
import pytest

from shallowspeed_tpu.observability import JsonlMetrics, read_jsonl
from shallowspeed_tpu.observability import costmodel, report
from shallowspeed_tpu.observability.metrics import SCHEMA_VERSION

SIZES = (24, 20, 18, 16, 14, 12, 11, 10)
N, GBS = 256, 64


@pytest.fixture()
def data_dir(tmp_path):
    rng = np.random.RandomState(0)
    for suffix, n in (("train", N), ("val", 96)):
        x = rng.randn(n, SIZES[0]).astype(np.float32)
        y = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], n)]
        np.save(tmp_path / f"x_{suffix}.npy", x)
        np.save(tmp_path / f"y_{suffix}.npy", y)
    return tmp_path


# ---------------------------------------------------------------------------
# cost model
# ---------------------------------------------------------------------------


def test_analytical_flops_single_source_of_truth():
    """bench.flops_per_sample and the cost model must be the same number."""
    # direct formula check: 6 * sum(in*out)
    assert costmodel.mlp_train_flops_per_sample((3, 4, 5)) == 6 * (12 + 20)
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "bench_for_report_test",
        Path(__file__).resolve().parent.parent / "bench.py",
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    assert bench.flops_per_sample() == costmodel.mlp_train_flops_per_sample(
        bench.SIZES
    )


def test_peak_flops_table_and_env_override(monkeypatch):
    peak, src = costmodel.peak_flops_per_chip("tpu", "default")
    assert peak == 200e12 and src == "datasheet-v5e"
    peak, src = costmodel.peak_flops_per_chip("axon", "highest")
    assert peak == 100e12  # the tunnel's TPU is a TPU
    peak, src = costmodel.peak_flops_per_chip("cpu", "highest")
    assert peak and src == "nominal-cpu-default"
    peak, src = costmodel.peak_flops_per_chip("gpu", "highest")
    assert peak is None and "unknown" in src
    monkeypatch.setenv(costmodel.ENV_PEAK, "5e12")
    peak, src = costmodel.peak_flops_per_chip("gpu", "highest")
    assert peak == 5e12 and src.startswith("env:")


def test_cost_model_mfu_arithmetic(monkeypatch):
    monkeypatch.setenv(costmodel.ENV_PEAK, "1e9")
    cm = costmodel.CostModel(
        sizes=(3, 4, 5), global_batch=10, batches_per_epoch=7, n_devices=4
    )
    fps = costmodel.mlp_train_flops_per_sample((3, 4, 5))
    assert cm.flops_per_epoch == fps * 10 * 7
    assert cm.achieved_flops_per_sec(100.0) == 100.0 * fps
    # MFU divides by peak x devices
    assert cm.mfu(100.0) == pytest.approx(100.0 * fps / (1e9 * 4))
    rec = cm.as_record()
    json.dumps(rec)  # JSON-able as-is
    assert rec["peak_source"].startswith("env:")
    assert rec["flops_ratio"] is None  # no compiled program attached yet


def test_cost_model_xla_crosscheck_on_real_compile():
    """Compiled.cost_analysis() of a real sequential epoch program attaches
    and yields a positive FLOP count (the cross-check leg); skipped when
    this jax/backend exposes no cost analysis."""
    import jax
    import jax.numpy as jnp

    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import trainer
    from shallowspeed_tpu.optimizer import SGD

    B, M = 32, 4
    spec = Mo.make_model_spec(SIZES, 1, B)
    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.rand(2, M, B // M, SIZES[0]).astype(np.float32))
    Y = jnp.asarray(
        np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], (2, M, B // M))]
    )
    params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
    epoch = trainer.make_train_epoch(spec, SGD(0.01))
    compiled = epoch.lower(params, (), X, Y).compile()
    flops, _ = costmodel.compiled_flops(compiled)
    if flops is None:
        pytest.skip("backend exposes no cost_analysis flops")
    cm = costmodel.CostModel(sizes=SIZES, global_batch=B, batches_per_epoch=2)
    assert cm.attach_compiled(compiled)
    assert cm.xla_flops_per_epoch > 0
    # structural cross-check only: scan bodies are counted once by XLA's
    # analysis, so the ratio sits well below 1 but must stay sane
    assert 0 < cm.flops_ratio < 100


def test_pipeline_padded_flops_from_tick_tables():
    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import schedules as S
    from shallowspeed_tpu.parallel.executor import slot_shapes
    from shallowspeed_tpu.parallel.lowering import lower_schedule, program_flops

    B, M, P = 32, 4, 4
    spec = Mo.make_model_spec(SIZES, P, B)
    prog = lower_schedule(S.GPipeSchedule, M, P)
    mb = B // M
    flops = program_flops(prog, spec, mb)
    # every device runs M forwards (2x) + M backwards (4x) over the padded
    # slot stack: (2*M*P + 4*M*P) * mb * padded_P
    padded_p = sum(o * i for o, i in slot_shapes(spec))
    assert flops == (2 * M * P + 4 * M * P) * mb * padded_p
    # the padded program always does at least the logical work
    assert flops >= costmodel.mlp_train_flops_per_sample(SIZES) * B


# ---------------------------------------------------------------------------
# report CLI
# ---------------------------------------------------------------------------


def _train_jsonl(data_dir, tmp_path, name, epochs=2):
    from shallowspeed_tpu.api import TrainingSession

    path = tmp_path / name
    with JsonlMetrics(path) as m:
        run = TrainingSession(
            sizes=SIZES, global_batch_size=GBS, lr=0.01, data_dir=data_dir,
            metrics=m, health="record", clip_norm=1.0,
        )
        for _ in range(epochs):
            run.train_epoch()
    return path


def test_report_round_trip_on_real_run(data_dir, tmp_path, capsys):
    """The acceptance contract: a fresh train_epoch JSONL renders MFU, the
    span breakdown and a health verdict, and the CLI exits 0."""
    path = _train_jsonl(data_dir, tmp_path, "run.jsonl")
    assert report.main([str(path), "--format", "md"]) == 0
    out = capsys.readouterr().out
    assert "MFU" in out and "%" in out
    assert "Span breakdown" in out
    assert "train_epoch" in out and "jit_compile" in out
    assert "health" in out and "ok" in out
    assert "Step loss" in out  # sparkline section

    # json format is machine-parseable and carries the same facts
    assert report.main([str(path), "--format", "json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["steps"] == 2 * 4  # 2 epochs x 4 batches
    assert rep["throughput_samples_per_sec"] > 0
    assert rep["mfu"] is not None and rep["health"]["verdict"] == "ok"
    assert rep["cost_model"]["flops_per_sample"] == (
        costmodel.mlp_train_flops_per_sample(SIZES)
    )
    assert rep["steady_epochs"] == 1  # first epoch includes compile

    # text format renders too
    assert report.main([str(path), "--format", "text"]) == 0


def test_report_baseline_regression_gate(data_dir, tmp_path, capsys):
    """--baseline exits nonzero (2) on an injected >10% throughput
    regression and 0 when within the threshold."""
    path = _train_jsonl(data_dir, tmp_path, "cur.jsonl")
    records = read_jsonl(path)
    cur = report.build_report(records)["throughput_samples_per_sec"]

    def synth_baseline(name, sps):
        p = tmp_path / name
        with JsonlMetrics(p) as m:
            m.event("epoch", epoch=0, loss=0.5, samples_per_sec=sps, wall_s=1.0)
        return p

    fast = synth_baseline("fast.jsonl", cur * 1.5)  # we regressed >10% vs this
    slow = synth_baseline("slow.jsonl", cur * 0.95)  # within threshold
    assert report.main([str(path), "--baseline", str(fast)]) == 2
    assert "REGRESSION" in capsys.readouterr().err
    assert report.main([str(path), "--baseline", str(slow)]) == 0
    # a generous threshold un-gates the fast baseline
    assert (
        report.main([str(path), "--baseline", str(fast), "--threshold", "0.9"]) == 0
    )

    # bench-style JSON baselines work too
    bench_rec = tmp_path / "bench.json"
    bench_rec.write_text(
        json.dumps({"metric": "x", "value": cur * 2.0, "unit": "samples/s"})
    )
    assert report.main([str(path), "--baseline", str(bench_rec)]) == 2
    capture_rec = tmp_path / "cap.json"
    capture_rec.write_text(json.dumps({"headline_best_sps": cur * 0.5}))
    assert report.main([str(path), "--baseline", str(capture_rec)]) == 0
    # a baseline with no recognizable throughput is a load error (1)
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"published": {}}))
    assert report.main([str(path), "--baseline", str(empty)]) == 1


def test_report_regression_gate_skipped_for_compile_polluted_runs(
    tmp_path, capsys
):
    """A run whose ONLY epoch record includes compile time must not be
    gated against a steady-state baseline — that would flag a spurious
    regression on every 1-epoch job."""
    short = tmp_path / "short.jsonl"
    with JsonlMetrics(short) as m:
        m.event("epoch", epoch=0, loss=0.5, samples_per_sec=100.0,
                wall_s=10.0, includes_compile=True)
    base = tmp_path / "steady.jsonl"
    with JsonlMetrics(base) as m:
        m.event("epoch", epoch=3, loss=0.5, samples_per_sec=1000.0, wall_s=1.0)
    assert report.main([str(short), "--baseline", str(base)]) == 0
    err = capsys.readouterr().err
    assert "regression gate skipped" in err
    rep = report.build_report(read_jsonl(short))
    assert rep["throughput_includes_compile"] is True
    # the asymmetric direction: a compile-polluted BASELINE must be
    # refused, not silently trusted (an understated baseline would let
    # real regressions pass the gate)
    assert report.main([str(base), "--baseline", str(short)]) == 1
    assert "compile-polluted" in capsys.readouterr().err


def test_report_mfu_carries_compile_caveat(tmp_path, capsys):
    path = tmp_path / "one.jsonl"
    with JsonlMetrics(path) as m:
        m.event("epoch", epoch=0, loss=0.5, samples_per_sec=100.0,
                wall_s=10.0, includes_compile=True, mfu=0.01)
    rep = report.build_report(read_jsonl(path))
    assert rep["mfu"] == 0.01 and rep["mfu_includes_compile"] is True
    assert report.main([str(path), "--format", "text"]) == 0
    out = capsys.readouterr().out
    assert "(includes compile)" in out


def test_report_accepts_schema_v1_files(tmp_path, capsys):
    """The v3 reader/report accept v1 files unchanged (compat rule)."""
    path = tmp_path / "v1.jsonl"
    v1 = [
        {"v": 1, "ts": 0.0, "kind": "meta", "name": "metrics",
         "schema": "shallowspeed_tpu.metrics"},
        {"v": 1, "ts": 1.0, "kind": "event", "name": "epoch", "epoch": 0,
         "loss": 0.4, "samples_per_sec": 1234.0, "wall_s": 1.0},
        {"v": 1, "ts": 2.0, "kind": "span", "name": "train_epoch",
         "path": "train_epoch", "depth": 0, "seconds": 1.0},
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in v1))
    recs = read_jsonl(path)  # strict: v1 < v2 is fine
    assert len(recs) == 3
    assert report.main([str(path), "--format", "text"]) == 0
    out = capsys.readouterr().out
    assert "1,234" in out
    # and a NEWER schema is still refused loudly
    future = tmp_path / "future.jsonl"
    future.write_text(json.dumps({"v": SCHEMA_VERSION + 1, "kind": "event"}) + "\n")
    assert report.main([str(future)]) == 1


def test_report_flags_nan_steps_and_halt_verdict(tmp_path, capsys):
    path = tmp_path / "nan.jsonl"
    with JsonlMetrics(path) as m:
        m.event("epoch", epoch=0, loss=float("nan"), samples_per_sec=10.0,
                wall_s=1.0)
        for i, loss in enumerate([0.5, 0.4, float("nan"), 9.0]):
            m.step("train", step=i, epoch=0, loss=loss)
        m.health("non_finite", epoch=0, step=2, value=None, action="halt",
                 detail="loss is nan")
    assert report.main([str(path), "--format", "md"]) == 0
    out = capsys.readouterr().out
    assert "HALTED: non_finite at epoch 0, step 2" in out
    assert "NON-FINITE" in out
    assert "x" in report.sparkline([0.5, float("nan"), 0.5])


def _audit_record_with_bounds():
    """A minimal xla_audit record carrying the comms model's overlap
    fields (the shape TrainingSession(audit=True) emits)."""
    return {
        "v": SCHEMA_VERSION, "ts": 0.0, "kind": "xla_audit",
        "name": "epoch_program", "hlo_available": True,
        "census": {"all_reduce": {"count": 3, "bytes": 3072}},
        "memory": None, "n_devices": 2,
        "expected": {
            "dp": 2, "pp": 1, "zero1": False, "sequential": False,
            "required": ["all_reduce"], "forbidden": [],
            "axes": {"dp": {"kind": "all_reduce", "mode": "bucketed",
                            "num_buckets": 3,
                            "grad_bucket_bytes": 1024,
                            "bucket_grad_bytes": [1024, 1024, 1024],
                            "bytes_per_step_per_device": 3072}},
            "bytes_per_step_per_device": 3072,
            "comms_time_per_step_s": 4e-6,
            "compute_time_per_step_s": 1e-6,
            "bound": "comms",
            "serial_bound_s": 5e-6,
            "overlapped_bound_s": 4e-6,
            "model_hidden_comm_share": 0.25,
        },
        "mismatches": [], "census_ok": True,
    }


def test_report_overlap_row_model_and_measured(tmp_path, capsys):
    """The overlap-efficiency row: the comms model's hidden-comm bound by
    default, upgraded to the measured trace split when one is given."""
    path = tmp_path / "ov.jsonl"
    path.write_text(json.dumps(_audit_record_with_bounds()) + "\n")
    assert report.main([str(path), "--format", "text"]) == 0
    out = capsys.readouterr().out
    assert "overlap efficiency" in out
    assert "25.00% of comm hideable (model bound; 3 buckets)" in out
    assert "serial (anchor)" in out and "max(comm, compute)" in out

    records = read_jsonl(path)
    rep = report.build_report(
        records,
        trace={
            "overlap_efficiency": 0.87, "comm_ms": 10.0,
            "exposed_comm_ms": 1.3, "comm_fraction": 0.2,
        },
    )
    assert rep["overlap"]["source"] == "measured"
    assert rep["overlap"]["hidden_comm_share"] == 0.87
    # the model's bounds survive alongside the measured share
    assert rep["overlap"]["serial_bound_s"] == 5e-6
    out = report.render(rep, "text")
    assert "87.00% of comm hidden (measured" in out


def test_report_trace_flag_measures_overlap(tmp_path, capsys):
    """--trace: a chrome trace's comm/compute split feeds the measured
    overlap-efficiency row (exposed = span not coverable by compute)."""
    import gzip

    trace = {
        "traceEvents": [
            {"ph": "M", "pid": 1, "name": "process_name",
             "args": {"name": "/device:TPU:0"}},
            # comm spans the full 100 us; compute covers 60 of them ->
            # 40 us exposed of 100 us comm -> 60% hidden
            {"ph": "X", "pid": 1, "tid": 1, "name": "all-reduce.1",
             "ts": 0, "dur": 100},
            {"ph": "X", "pid": 1, "tid": 2, "name": "fusion.2",
             "ts": 0, "dur": 60},
        ]
    }
    tpath = tmp_path / "x.trace.json.gz"
    with gzip.open(tpath, "wt") as f:
        json.dump(trace, f)
    from shallowspeed_tpu.observability import trace_stats

    s = trace_stats.summarize(tpath)
    assert s["comm_ms"] == 0.1 and s["compute_ms"] == 0.06
    assert s["exposed_comm_ms"] == pytest.approx(0.04)
    assert s["overlap_efficiency"] == pytest.approx(0.6)

    path = tmp_path / "run.jsonl"
    path.write_text(json.dumps(_audit_record_with_bounds()) + "\n")
    assert report.main(
        [str(path), "--format", "text", "--trace", str(tmp_path)]
    ) == 0
    out = capsys.readouterr().out
    assert "60.00% of comm hidden (measured" in out


def test_trace_overlap_survives_multidevice_and_unit_overlap(tmp_path):
    """The exposure math is a per-device interval union, so it is not
    fooled by (a) several device pids sharing one wall span or (b)
    functional-unit overlap where summed busy time exceeds the span —
    busy-sum arithmetic would report exposed=0 for any such trace."""
    import gzip

    trace = {
        "traceEvents": [
            {"ph": "M", "pid": 1, "name": "process_name",
             "args": {"name": "/device:TPU:0"}},
            {"ph": "M", "pid": 2, "name": "process_name",
             "args": {"name": "/device:TPU:1"}},
            # device 0: comm [0,100], compute [0,40]+[20,60] on two unit
            # threads (busy 100+40+40=180 > span 100) -> union(compute) =
            # [0,60], exposed comm = 40
            {"ph": "X", "pid": 1, "tid": 1, "name": "all-reduce.1",
             "ts": 0, "dur": 100},
            {"ph": "X", "pid": 1, "tid": 2, "name": "fusion.1",
             "ts": 0, "dur": 40},
            {"ph": "X", "pid": 1, "tid": 3, "name": "fusion.2",
             "ts": 20, "dur": 40},
            # device 1: comm [0,50] + comm [25,75] (mutually overlapping
            # — must NOT count as hidden: the union, 75, is the
            # denominator) fully under compute [0,100] -> 0 exposed
            # (device 0's compute must NOT be credited here either)
            {"ph": "X", "pid": 2, "tid": 1, "name": "all-reduce.2",
             "ts": 0, "dur": 50},
            {"ph": "X", "pid": 2, "tid": 3, "name": "all-reduce.3",
             "ts": 25, "dur": 50},
            {"ph": "X", "pid": 2, "tid": 2, "name": "fusion.3",
             "ts": 0, "dur": 100},
        ]
    }
    tpath = tmp_path / "multi.trace.json.gz"
    with gzip.open(tpath, "wt") as f:
        json.dump(trace, f)
    from shallowspeed_tpu.observability import trace_stats

    s = trace_stats.summarize(tpath)
    assert s["comm_ms"] == pytest.approx(0.2)  # summed busy time
    assert s["comm_union_ms"] == pytest.approx(0.175)  # 100 + 75
    assert s["exposed_comm_ms"] == pytest.approx(0.04)
    # hidden share over the comm interval UNION: 1 - 40/175
    assert s["overlap_efficiency"] == pytest.approx(1 - 40 / 175, abs=1e-3)


def test_sparkline_shapes():
    assert report.sparkline([]) == ""
    assert len(report.sparkline(list(range(1000)), width=60)) == 60
    flat = report.sparkline([2.0, 2.0, 2.0])
    assert len(set(flat)) == 1  # constant series renders uniformly
    line = report.sparkline([1, 2, 3, 4, 5, 6, 7, 8])
    assert line[0] == report.BLOCKS[0] and line[-1] == report.BLOCKS[-1]


def test_report_unreadable_run_exits_1(tmp_path, capsys):
    missing = tmp_path / "nope.jsonl"
    assert report.main([str(missing)]) == 1
    assert "cannot read" in capsys.readouterr().err


def test_report_without_audit_records_omits_sections(tmp_path, capsys):
    """No xla_audit record -> no Memory/Comms sections (and no crash);
    the JSON rendering carries xla_audit: null so consumers can tell
    'not audited' from 'audited clean'."""
    path = tmp_path / "plain.jsonl"
    with JsonlMetrics(path) as m:
        m.event("epoch", epoch=0, loss=0.5, samples_per_sec=10.0, wall_s=1.0)
    rep = report.build_report(read_jsonl(path))
    assert rep["xla_audit"] is None
    assert report.main([str(path), "--format", "md"]) == 0
    out = capsys.readouterr().out
    assert "Memory (compiled program)" not in out
    assert "Comms (XLA program audit)" not in out
    assert report.main([str(path), "--format", "json"]) == 0
    assert json.loads(capsys.readouterr().out)["xla_audit"] is None


def test_report_weighted_bubble_row(tmp_path, capsys):
    """The pipeline_program event's FLOP-weighted bubble renders as its
    own row — tagged with the split-backward note when the program
    deferred its weight grads, the plain FLOP-weighted note otherwise."""
    for split in (False, True):
        path = tmp_path / f"run_{split}.jsonl"
        with JsonlMetrics(path) as m:
            m.event(
                "pipeline_program", schedule="pipedream", dp=1, pp=4,
                bubble_fraction=0.27 if not split else 0.11,
                weighted_bubble_fraction=0.40 if not split else 0.11,
                backward_split=split,
            )
            m.event("epoch", epoch=0, loss=0.5, samples_per_sec=10.0, wall_s=1.0)
        rep = report.build_report(read_jsonl(path))
        assert rep["weighted_bubble_fraction"] == (0.40 if not split else 0.11)
        assert rep["backward_split"] is split
        assert report.main([str(path), "--format", "md"]) == 0
        out = capsys.readouterr().out
        assert "weighted bubble" in out
        if split:
            assert "split backward" in out
        else:
            assert "FLOP-weighted ticks" in out


def test_report_reads_multihost_shard_glob(tmp_path, capsys):
    """The report CLI accepts a glob of multihost JSONL shards (and the
    bare-path fallback): per-host epoch records merge into one report."""
    for idx, loss in ((0, 0.5), (1, 0.25)):
        (tmp_path / f"run.jsonl.p{idx}").write_text(
            json.dumps({"v": SCHEMA_VERSION, "ts": float(idx), "kind": "event",
                        "name": "epoch", "epoch": 0, "loss": loss,
                        "samples_per_sec": 100.0, "wall_s": 1.0}) + "\n"
        )
    glob_arg = str(tmp_path / "run.jsonl.p*")
    assert report.main([glob_arg, "--format", "json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["epochs"] == 2
    # bare path that never existed resolves to its shards
    assert report.main([str(tmp_path / "run.jsonl"), "--format", "json"]) == 0
    assert json.loads(capsys.readouterr().out)["epochs"] == 2


def test_report_reliability_section(tmp_path, capsys):
    """The schema-v4 Reliability story: checkpoint overhead + cadence from
    the checkpoint records, and the recovery verdict with steps-lost-to-
    replay MEASURED from the killed run's step records when the streams
    are concatenated (the `make recovery-smoke` shape)."""
    killed = tmp_path / "killed.jsonl"
    with JsonlMetrics(killed) as m:
        with m.span("train_steps"):
            pass
        for gs in (4, 8):
            m.checkpoint(
                "step", path=f"/ck/step-{gs:08d}.npz", epoch=0,
                step_in_epoch=gs, global_step=gs, bytes=4096, wall_s=0.25,
            )
        for s in range(12):  # the dead run trained through step 11
            m.step("train", step=s, epoch=0, loss=0.5)
    resumed = tmp_path / "resumed.jsonl"
    with JsonlMetrics(resumed) as m:
        m.recovery(
            "resumed", resumed_from="/ck/step-00000008.npz", epoch=0,
            step_in_epoch=8, global_step=8,
            skipped=[{"path": "/ck/step-00000012.npz",
                      "cause": "content checksum mismatch"}],
        )
        m.event("epoch", epoch=0, loss=0.4, samples_per_sec=10.0, wall_s=1.0)
    combined = tmp_path / "combined.jsonl"
    combined.write_text(killed.read_text() + resumed.read_text())

    rep = report.build_report(read_jsonl(combined))
    rel = rep["reliability"]
    assert rel["checkpoints"] == 2
    assert rel["checkpoint_wall_s"] == pytest.approx(0.5)
    assert 0 < rel["checkpoint_overhead_fraction"] <= 1
    assert rel["checkpoint_cadence_steps"] == 4
    assert rel["recovery"]["verdict"] == "resumed"
    # the kill happened after step 11 trained, the restore landed on 8
    assert rel["recovery"]["steps_lost_to_replay"] == 12 - 8
    assert rel["recovery"]["skipped"][0]["cause"] == "content checksum mismatch"

    assert report.main([str(combined), "--format", "md"]) == 0
    out = capsys.readouterr().out
    assert "## Reliability" in out
    assert "recovery: resumed from /ck/step-00000008.npz" in out
    assert "steps lost to replay: 4" in out
    assert "1 corrupt snapshot(s) skipped" in out

    # the resumed stream ALONE has no step evidence before the recovery
    # record: the loss is honestly unknown, never guessed
    rep2 = report.build_report(read_jsonl(resumed))
    assert rep2["reliability"]["recovery"]["steps_lost_to_replay"] is None
    assert report.main([str(resumed), "--format", "md"]) == 0
    assert "steps lost to replay: unknown" in capsys.readouterr().out

    # a kill that landed exactly on a checkpointed step is a MEASURED 0,
    # not unknown — the killed run's evidence IS in the stream
    zero = tmp_path / "zero.jsonl"
    with JsonlMetrics(zero) as m:
        for s in range(8):  # trained through step 7, snapshot at 8
            m.step("train", step=s, epoch=0, loss=0.5)
        m.recovery(
            "resumed", resumed_from="/ck/step-00000008.npz", epoch=0,
            step_in_epoch=8, global_step=8, skipped=[],
        )
    rep3 = report.build_report(read_jsonl(zero))
    assert rep3["reliability"]["recovery"]["steps_lost_to_replay"] == 0
    assert report.main([str(zero), "--format", "md"]) == 0
    assert "steps lost to replay: 0" in capsys.readouterr().out


def test_report_reliability_omitted_without_v4_records(tmp_path, capsys):
    """Pre-v4 runs render exactly as before: reliability is null in JSON
    and the section is absent from the text rendering; a fresh_start
    recovery renders its own verdict line."""
    plain = tmp_path / "plain.jsonl"
    with JsonlMetrics(plain) as m:
        m.event("epoch", epoch=0, loss=0.5, samples_per_sec=10.0, wall_s=1.0)
    assert report.build_report(read_jsonl(plain))["reliability"] is None
    assert report.main([str(plain), "--format", "md"]) == 0
    assert "Reliability" not in capsys.readouterr().out

    fresh = tmp_path / "fresh.jsonl"
    with JsonlMetrics(fresh) as m:
        m.recovery("fresh_start", resumed_from=None, epoch=0,
                   step_in_epoch=0, global_step=0, skipped=[])
        m.event("epoch", epoch=0, loss=0.5, samples_per_sec=10.0, wall_s=1.0)
    assert report.main([str(fresh), "--format", "md"]) == 0
    out = capsys.readouterr().out
    assert "recovery: fresh start" in out


def test_report_fleet_section(tmp_path, capsys):
    """The Fleet section: a v7 fleet summary + fleet_health stream renders
    replica lifecycle, failover, elasticity, routing skew, per-replica
    verdict rows and the availability verdict; runs without fleet records
    render exactly as before (fleet is null / section absent)."""
    path = tmp_path / "fleet.jsonl"
    with JsonlMetrics(path) as m:
        for rid in (0, 1, 2):
            m.fleet_health("replica_spawned", replica_id=rid, checkpoint=None)
            m.fleet_health("replica_ready", replica_id=rid, wall_s=1.2)
        m.fleet_health("replica_sigkill", replica_id=1, pid=123)
        m.fleet_health("replica_dead", replica_id=1, inflight=3, error=None)
        m.fleet_health("failover", replica_id=1, requeued=3, exhausted=0)
        m.fleet_health("scale_up", replica_id=3, replacement=True, target=3)
        m.fleet(
            "summary",
            completed=90, dropped=0, expired=0, errors=0, unhealthy=0,
            availability=1.0, failovers=1, failover_requeued=3,
            failover_exhausted=0, reroutes=2, replicas_target=3,
            replicas_started=4, replicas_ready=3, replicas_dead=1,
            replicas_retired=0, scale_ups=1, scale_downs=0,
            scale_up_s=1.4, degraded=False, recovery_s=0.004,
            routing={0: 44, 1: 6, 2: 40, 3: 0}, routing_skew=1.47,
            per_replica={
                0: {"state": "ready", "routed": 44, "verdicts": {"ok": 44}},
                1: {"state": "dead", "routed": 6, "verdicts": {"ok": 5}},
            },
            p50_latency_s=0.004, p99_latency_s=0.012,
        )
    rep = report.build_report(read_jsonl(path))
    fl = rep["fleet"]
    assert fl["failovers"] == 1 and fl["failover_requeued"] == 3
    assert fl["sigkills_injected"] == 1
    assert fl["degraded_at_exit"] is False
    assert "recovered from 1 replica death" in fl["verdict"]
    assert report.main([str(path), "--format", "md"]) == 0
    out = capsys.readouterr().out
    assert "## Fleet" in out
    assert "1 DIED (1 SIGKILL injected)" in out
    assert "failover: 1 event(s), 3 in-flight request(s) re-queued" in out
    assert "skew 1.47x" in out
    assert "replica 1 [dead]" in out
    assert "availability 100.0%" in out

    # killed-parent fallback: fleet_health events alone still fold
    partial = tmp_path / "partial.jsonl"
    with JsonlMetrics(partial) as m:
        m.fleet_health("replica_spawned", replica_id=0, checkpoint=None)
        m.fleet_health("replica_dead", replica_id=0, inflight=2, error=None)
        m.fleet_health("failover", replica_id=0, requeued=2, exhausted=0)
        m.fleet_health("fleet_degraded", replica_id=None, healthy=0,
                       target=1, quorum=1)
    fl2 = report.build_report(read_jsonl(partial))["fleet"]
    assert fl2["replicas_dead"] == 1 and fl2["failover_requeued"] == 2
    assert fl2["degraded_at_exit"] is True
    assert "DEGRADED" in fl2["verdict"]

    # no fleet records -> section omitted, JSON carries fleet: null
    plain = tmp_path / "noval.jsonl"
    with JsonlMetrics(plain) as m:
        m.event("epoch", epoch=0, loss=0.5, samples_per_sec=10.0, wall_s=1.0)
    assert report.build_report(read_jsonl(plain))["fleet"] is None
    assert report.main([str(plain), "--format", "md"]) == 0
    assert "## Fleet" not in capsys.readouterr().out


def test_report_reliability_async_and_aot_rows(tmp_path, capsys):
    """The schema-v8 Reliability additions: async saves render their
    off-path accounting next to the (now on-path-only) overhead
    fraction, the aot_cache records fold into a hit-rate row with the
    degraded outcomes named, and the Degradation breaker line carries
    the reload's single-read verify time."""
    path = tmp_path / "v8.jsonl"
    with JsonlMetrics(path) as m:
        with m.span("train_steps"):
            pass
        for gs in (4, 8):
            m.checkpoint(
                "step", path=f"/ck/step-{gs:08d}.npz", epoch=0,
                step_in_epoch=gs, global_step=gs, bytes=4096,
                wall_s=0.002, **{"async": True}, queue_depth=1,
                verify_s=0.1, write_s=0.15, queued_s=0.001,
            )
        m.aot_cache("miss", program="inference_r4", key="k1")
        m.aot_cache("store", program="inference_r4", key="k1", bytes=100)
        m.aot_cache("hit", program="inference_r4", key="k1", wall_s=0.004)
        m.aot_cache("hit", program="inference_r8", key="k2", wall_s=0.006)
        m.aot_cache(
            "corrupt", program="inference_r2", key="k3",
            reason="payload sha256 mismatch",
        )
    rep = report.build_report(read_jsonl(path))
    rel = rep["reliability"]
    assert rel["checkpoints_async"] == 2
    assert rel["checkpoint_off_path_s"] == pytest.approx(0.5)
    # on-path wall only: async saves cost milliseconds on the step path
    assert rel["checkpoint_wall_s"] == pytest.approx(0.004)
    aot = rel["aot_cache"]
    assert aot["hits"] == 2 and aot["misses"] == 1
    assert aot["hit_rate"] == pytest.approx(2 / 3)
    assert aot["corrupt"] == 1 and aot["stores"] == 1

    assert report.main([str(path), "--format", "md"]) == 0
    out = capsys.readouterr().out
    assert "async checkpointing: 2 of 2 saves off-path" in out
    assert "aot executable cache: 2 hit(s) / 1 miss(es)" in out
    assert "hit rate 67%" in out
    assert "1 corrupt entr(ies) fell back to a clean recompile" in out

    # an aot-only stream (a serving replica) still gets the section
    aot_only = tmp_path / "aot_only.jsonl"
    with JsonlMetrics(aot_only) as m:
        m.aot_cache("hit", program="inference_r4", key="k1", wall_s=0.004)
    rep2 = report.build_report(read_jsonl(aot_only))
    assert rep2["reliability"]["aot_cache"]["hits"] == 1

    # reload verify accounting reaches the Degradation breaker line
    deg = tmp_path / "deg.jsonl"
    with JsonlMetrics(deg) as m:
        m.serving("summary", completed=5, dropped=0, breaker_trips=1,
                  reloads=1, recovery_s=0.02)
        m.serving_health("breaker_open", dispatch=3, consecutive_failures=3)
        m.reload("ok", path="/ck/step-00000008.npz", step=8,
                 reason="breaker", wall_s=0.03, verify_s=0.012)
    rep3 = report.build_report(read_jsonl(deg))
    assert rep3["serving"]["degradation"]["reload_verify_s"] == pytest.approx(
        0.012
    )
    assert report.main([str(deg), "--format", "md"]) == 0
    out3 = capsys.readouterr().out
    assert "snapshot verify" in out3 and "single-read" in out3
