"""Summarize a jax.profiler chrome-trace: the roofline evidence extractor.

Parses the ``*.trace.json.gz`` a capture leaves in artifacts/tpu_trace*/ and
reports the numbers docs/performance.md's roofline section rests on — device
op count, wall span, per-op issue rate, functional-unit overlap, and the op
breakdown — so the "latency-roofline" verdict is recomputable from the
committed artifact instead of hand-derived prose.

Importable (promoted from scripts/ — ``scripts/trace_stats.py`` remains as a
thin CLI shim):

    from shallowspeed_tpu.observability import trace_stats
    stats = trace_stats.summarize("artifacts/.../xyz.trace.json.gz")

CLI (same surface as before):

    python scripts/trace_stats.py artifacts/tpu_trace
    python scripts/trace_stats.py path/to/xyz.trace.json.gz --json
"""

import argparse
import collections
import gzip
import json
import sys
from pathlib import Path

# Device-op name prefixes that are COMMUNICATION, not compute — the HLO
# collective spellings (incl. their async -start/-done halves) plus the
# point-to-point ops. Everything else on the device timeline counts as
# compute, so ``comm_fraction`` is directly comparable against the
# analytical comms model's bound verdict (program_audit.expected_comms).
COMM_OP_PREFIXES = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
    "collective-broadcast",
    "send",
    "recv",
)


def is_comm_op(name):
    """True when a device-op name is a communication op (collective or
    point-to-point), by HLO-name prefix."""
    n = str(name).lower()
    return n.startswith(COMM_OP_PREFIXES)


def _union_us(events):
    """Total covered length of the [ts, ts+dur) intervals of ``events``."""
    covered, end = 0.0, None
    for s, e in sorted((ev["ts"], ev["ts"] + ev.get("dur", 0)) for ev in events):
        if end is None or s > end:
            covered += e - s
            end = e
        elif e > end:
            covered += e - end
            end = e
    return covered


def find_traces(path):
    """A file path as-is, or every ``*.trace.json.gz`` under a directory."""
    p = Path(path)
    if p.is_file():
        return [p]
    return sorted(p.rglob("*.trace.json.gz"))


# CPU-backend fallback for the dispatch-overhead probe: jax's TFRT CPU
# client emits NO "/device:" pid — the HLO thunk executions land on the
# host pid's XLA executor threads instead (the Eigen worker pool plus the
# client thread, all named "tf_XLA...").
CPU_EXECUTOR_THREAD_PREFIX = "tf_XLA"


def _is_hlo_thunk_event(name):
    """True when an executor-thread event is an HLO op execution (e.g.
    ``dot.14``, ``broadcast_maximum_fusion.clone``, ``call.1``) rather
    than runtime plumbing: C++ internals carry ``::`` (including the
    ``ThunkExecutor::Execute (wait for completion)`` WAIT, which is idle
    time, not compute), python frames are prefixed ``$``, and
    ``ParseArguments`` is argument marshalling."""
    n = str(name)
    return not (n.startswith("$") or "::" in n or n == "ParseArguments")


def dispatch_busy(trace_path):
    """Op-execution interval UNION of one trace — the device-compute side
    of the dispatch-overhead probe (``api.measure_dispatch_overhead``).

    On a real accelerator trace this is the ``/device:`` pids' op stream
    (the same filter ``summarize`` uses). On the CPU backend — no
    ``/device:`` pid at all — it falls back to the HLO thunk events on
    the ``tf_XLA*`` executor threads. Either way the result is an
    interval union, not a busy-time sum: parallel Eigen workers (or
    overlapping functional units) must not let summed busy time exceed
    the wall and understate the dispatch share. The union is also split
    by ``is_comm_op`` so the probe's record carries the same
    comm/compute attribution as ``summarize``.

    Returns ``{"op_events", "busy_union_s", "comm_union_s",
    "compute_union_s", "source": "device"|"host-executor", "trace"}`` —
    ``op_events == 0`` (with ``busy_union_s`` None) when the trace holds
    nothing attributable, which callers must surface, not paper over.
    """
    with gzip.open(trace_path) as f:
        tr = json.load(f)
    events = tr.get("traceEvents", [])
    dev_pids = {
        e["pid"]
        for e in events
        if e.get("ph") == "M"
        and e.get("name") == "process_name"
        and "/device:" in str(e.get("args", {}).get("name", ""))
    }
    module_tids = {
        (e["pid"], e["tid"])
        for e in events
        if e.get("ph") == "M"
        and e.get("name") == "thread_name"
        and "Modules" in str(e.get("args", {}).get("name", ""))
    }
    if dev_pids:
        ops = [
            e
            for e in events
            if e.get("ph") == "X"
            and e.get("pid") in dev_pids
            and (e["pid"], e.get("tid")) not in module_tids
        ]
        source = "device"
    else:
        executor_tids = {
            (e["pid"], e["tid"])
            for e in events
            if e.get("ph") == "M"
            and e.get("name") == "thread_name"
            and str(e.get("args", {}).get("name", "")).startswith(
                CPU_EXECUTOR_THREAD_PREFIX
            )
        }
        ops = [
            e
            for e in events
            if e.get("ph") == "X"
            and (e.get("pid"), e.get("tid")) in executor_tids
            and _is_hlo_thunk_event(e.get("name"))
        ]
        source = "host-executor"
    if not ops:
        return {
            "trace": str(trace_path),
            "op_events": 0,
            "busy_union_s": None,
            "comm_union_s": None,
            "compute_union_s": None,
            "source": source,
        }
    busy_us = _union_us(ops)
    comm_us = _union_us(e for e in ops if is_comm_op(e["name"]))
    compute_us = _union_us(e for e in ops if not is_comm_op(e["name"]))
    return {
        "trace": str(trace_path),
        "op_events": len(ops),
        "busy_union_s": busy_us / 1e6,
        "comm_union_s": comm_us / 1e6,
        "compute_union_s": compute_us / 1e6,
        "source": source,
    }


def dispatch_overhead_share(busy_union_s, host_wall_s):
    """The measured op-issue roofline number: the share of the host wall
    NOT covered by op execution — ``1 - busy/wall``, clamped at 0 (timer
    jitter must not report negative overhead). ``None`` when either side
    is unmeasured; a probe that cannot attribute must say so instead of
    reporting a perfect 0."""
    if not host_wall_s or busy_union_s is None:
        return None
    return max(0.0, 1.0 - busy_union_s / host_wall_s)


def summarize(trace_path):
    """Device-op statistics for one chrome trace (dict, JSON-able).

    Keys: ``device_ops``, ``span_ms`` (first-op-start to last-op-end wall on
    the device timeline), ``busy_ms`` (summed op durations), ``ns_per_op_issued``
    (serial issue rate — the latency-roofline number), ``unit_overlap``
    (busy/span; >1 means functional units overlap, the op stream rather than
    FLOPs is the bottleneck when this is high while MXU% is low),
    ``top_ops`` (count per op-name prefix), and the comm/compute split —
    ``comm_ops`` / ``comm_ms`` / ``compute_ms`` / ``comm_fraction`` (comm
    busy time over total busy time, classified by ``is_comm_op``) — so the
    MEASURED communication share of a capture is directly comparable
    against the analytical comms model's verdict
    (program_audit.expected_comms). From the same split come the overlap
    numbers the bucketed gradient sync exists to move:
    ``exposed_comm_ms`` — timeline time where communication ran with NO
    compute op in flight on the same device (a per-pid interval-union
    sweep: ``|union(comm) \\ union(compute)|`` summed over device pids —
    busy-time arithmetic would be fooled by multi-device traces and by
    functional-unit overlap, where summed busy time exceeds the span) —
    and ``overlap_efficiency`` — the hidden-comm share
    ``1 - exposed_comm / comm_union`` (None when the trace has no comm
    ops; ``comm_union_ms`` — the comm-interval union — is the
    denominator rather than summed comm busy time, so collectives that
    merely overlap EACH OTHER do not count as hidden behind compute):
    1.0 means every communication microsecond rode behind compute, 0.0
    means the sync was fully serial. ``{"device_ops": 0}`` when the
    trace holds no device ops.
    """
    with gzip.open(trace_path) as f:
        tr = json.load(f)
    events = tr.get("traceEvents", [])
    # device pid: the process named like a device (e.g. '/device:TPU:0')
    dev_pids = {
        e["pid"]
        for e in events
        if e.get("ph") == "M"
        and e.get("name") == "process_name"
        and "/device:" in str(e.get("args", {}).get("name", ""))
    }
    # thread names, to exclude the whole-module envelope event from op stats
    module_tids = {
        (e["pid"], e["tid"])
        for e in events
        if e.get("ph") == "M"
        and e.get("name") == "thread_name"
        and "Modules" in str(e.get("args", {}).get("name", ""))
    }
    ops = [
        e
        for e in events
        if e.get("ph") == "X"
        and e.get("pid") in dev_pids
        and (e["pid"], e.get("tid")) not in module_tids
    ]
    if not ops:
        return {"trace": str(trace_path), "device_ops": 0}
    t0 = min(e["ts"] for e in ops)
    t1 = max(e["ts"] + e.get("dur", 0) for e in ops)
    span_us = t1 - t0
    busy_us = sum(e.get("dur", 0) for e in ops)
    comm = [e for e in ops if is_comm_op(e["name"])]
    comm_us = sum(e.get("dur", 0) for e in comm)
    kinds = collections.Counter(e["name"].split(".")[0] for e in ops)
    # exposed comm per DEVICE pid: comm-interval time not covered by any
    # compute interval on the same device — |union(all) - union(compute)|
    # (compute on another chip cannot hide this chip's collective, and
    # the interval union is immune to busy-sum > span unit overlap). The
    # efficiency denominator is the comm interval UNION, not summed busy
    # time: two collectives overlapping each other hide nothing behind
    # compute, and must not inflate the hidden share.
    exposed_us = 0.0
    comm_union_us = 0.0
    for pid in {e["pid"] for e in comm}:
        dev = [e for e in ops if e["pid"] == pid]
        compute_cover = _union_us(e for e in dev if not is_comm_op(e["name"]))
        exposed_us += _union_us(dev) - compute_cover
        comm_union_us += _union_us(e for e in dev if is_comm_op(e["name"]))
    return {
        "trace": str(trace_path),
        "device_ops": len(ops),
        "span_ms": round(span_us / 1e3, 3),
        "busy_ms": round(busy_us / 1e3, 3),
        # serial issue rate: ops retired per wall time on the device —
        # the latency-roofline number (238 ns/op measured round 2)
        "ns_per_op_issued": round(1e3 * span_us / len(ops), 1),
        # >1 means functional units overlap; the op stream, not FLOPs,
        # is the bottleneck when this is high while MXU% is low
        "unit_overlap": round(busy_us / span_us, 2),
        # the measured comm/compute split (busy-time attribution) — the
        # observed counterpart of the comms model's bound verdict
        "comm_ops": len(comm),
        "comm_ms": round(comm_us / 1e3, 3),
        "compute_ms": round((busy_us - comm_us) / 1e3, 3),
        "comm_fraction": round(comm_us / busy_us, 4) if busy_us else 0.0,
        # the measured overlap story (see the docstring): how much of the
        # comm timeline was exposed vs hidden behind compute
        "exposed_comm_ms": round(exposed_us / 1e3, 3),
        "comm_union_ms": round(comm_union_us / 1e3, 3),
        "overlap_efficiency": (
            round(1.0 - exposed_us / comm_union_us, 4)
            if comm_union_us
            else None
        ),
        "top_ops": dict(kinds.most_common(8)),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("path", help="trace dir or a *.trace.json.gz file")
    ap.add_argument("--json", action="store_true", help="one JSON line per trace")
    args = ap.parse_args(argv)
    traces = find_traces(args.path)
    if not traces:
        print(f"no *.trace.json.gz under {args.path}", file=sys.stderr)
        sys.exit(1)
    for t in traces:
        s = summarize(t)
        if args.json:
            from shallowspeed_tpu.observability.metrics import json_safe

            print(json.dumps(json_safe(s), allow_nan=False))
        else:
            print(f"{s['trace']}:")
            for k, v in s.items():
                if k != "trace":
                    print(f"  {k}: {v}")


if __name__ == "__main__":
    main()
