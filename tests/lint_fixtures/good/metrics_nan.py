"""SSP002 good twin: strict-JSON metrics writes."""

import json


def emit(record, f):
    f.write(json.dumps(record, allow_nan=False) + "\n")
