"""Inference serving engine: request queue + continuous batching into slots.

The ROADMAP's "millions of users" north star is a latency problem — requests
arrive one at a time and must be packed into the executor's fixed microbatch
slots on the fly, the same on-the-fly packing torchgpipe applies to training
microbatches (arXiv 2004.09910). ``ServingEngine`` owns that loop on top of
``TrainingSession``'s cached inference programs:

- **queue**: deadline-tagged requests of variable row counts, FIFO (packing
  is order-preserving so responses complete in arrival order — the
  determinism the bitwise-parity contract needs; deadlines tag accounting,
  they do not reorder);
- **continuous batching**: each ``step()`` packs the queue's head into the
  next dispatch — whole ``slot_rows``-row microbatch slots per request
  (requests never share a slot), up to ``max_slots`` slots, the slot count
  then rounded up the session's fixed ladder so at most ``len(ladder)``
  inference programs are ever compiled;
- **bitwise parity**: a slot's compute has one fixed shape in every rung
  program, so each response is bitwise-equal to a direct
  ``session.predict()`` of the same rows (measured, and asserted by
  ``make serve-smoke`` under seeded Poisson load);
- **steady-state weights**: every dispatch reads the SAME device-resident
  stacked params the session holds — weights are staged once at session
  construction and never re-transferred per request. Donation is
  deliberately NOT used here: the params are reused by the very next
  dispatch (and by training), so donating their buffers would be a
  use-after-free, not an optimization — steady-state residency comes from
  holding the arrays, the executor aliases them read-only;
- **accounting**: per-request enqueue -> dispatch -> complete timestamps,
  queue wait, padding waste, and a bounded queue-depth ring (the flight-
  recorder pattern) — emitted as schema-v5 ``request`` records plus a
  ``serving`` summary and a ``serving.queue_depth`` gauge when a metrics
  recorder is attached (docs/serving.md, docs/observability.md). The
  engine itself retains only SCALAR samples (latencies, waits, deadline
  tags) between ``reset_stats()`` calls — completed ``Request`` objects,
  with their input payloads and result arrays, are handed back to the
  caller by ``step()``/``drain()`` and never kept, so a long-lived engine
  does not grow with the traffic it has served.
"""

import time
from collections import deque

import numpy as np

from shallowspeed_tpu.observability import NullMetrics
from shallowspeed_tpu.serving import slots as serving_slots


class Request:
    """One queued inference request and its full accounting."""

    __slots__ = (
        "id",
        "x",
        "rows",
        "slots",
        "deadline_ms",
        "enqueue_t",
        "dispatch_t",
        "complete_t",
        "result",
        "verdict",
    )

    def __init__(self, req_id, x, slots, deadline_ms, enqueue_t):
        self.id = req_id
        self.x = x
        self.rows = int(x.shape[0])
        self.slots = int(slots)
        self.deadline_ms = deadline_ms
        self.enqueue_t = enqueue_t
        self.dispatch_t = None
        self.complete_t = None
        self.result = None  # (rows, out_dim) softmax probabilities
        self.verdict = "queued"  # -> "ok" | "dropped"

    @property
    def latency_s(self):
        """enqueue -> complete wall seconds (None until completed)."""
        if self.complete_t is None:
            return None
        return self.complete_t - self.enqueue_t

    @property
    def queue_s(self):
        """enqueue -> dispatch wall seconds (None until dispatched)."""
        if self.dispatch_t is None:
            return None
        return self.dispatch_t - self.enqueue_t

    def slo_ok(self, slo_ms=None):
        """Did this request meet its deadline (its own tag, else the
        engine-level SLO)? None when neither threshold exists or the
        request never completed."""
        bound = self.deadline_ms if self.deadline_ms is not None else slo_ms
        if bound is None or self.latency_s is None:
            return None
        return self.latency_s <= bound / 1000.0


class ServingEngine:
    """Continuous-batching serving loop over a session's inference programs.

    ``session``: a ``TrainingSession`` on any layout (its ``slot_rows`` /
    ``slot_ladder`` fix the dispatch geometry). ``max_slots``: packing
    capacity per dispatch (default: the ladder's top rung). ``slo_ms``: the
    engine-level latency objective requests are scored against when they
    carry no deadline of their own. ``max_queue``: admission bound —
    submissions beyond it are DROPPED (recorded, returned with verdict
    "dropped", never silently discarded); None = unbounded. ``clock`` is
    injectable for tests.
    """

    def __init__(
        self,
        session,
        max_slots=None,
        slo_ms=None,
        max_queue=None,
        metrics=None,
        clock=time.perf_counter,
        depth_ring=4096,
    ):
        self._session = session
        self._slot_rows = session.slot_rows
        self._ladder = session.slot_ladder
        self._max_slots = (
            int(max_slots) if max_slots is not None else self._ladder[-1]
        )
        if self._max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        if self._max_slots > self._ladder[-1]:
            # a dispatch larger than the top rung has no program to run on:
            # step() packs up to max_slots and then rounds up the ladder,
            # so admitting this would crash mid-traffic, not at configure
            # time
            raise ValueError(
                f"max_slots {self._max_slots} exceeds the slot ladder's top "
                f"rung {self._ladder[-1]} — extend the ladder instead"
            )
        self._slo_ms = slo_ms
        self._max_queue = max_queue
        self._metrics = metrics if metrics is not None else NullMetrics()
        self.clock = clock
        # sequential sessions dispatch only the OCCUPIED slots (one fixed
        # program per slot — no rung program to round up to), so the
        # padding accounting must not charge them the rung tail
        self._sequential = bool(getattr(session, "sequential", False))
        self._queue = deque()
        self._next_id = 0
        # the flight-recorder pattern: a bounded ring of (t, queue_depth)
        # samples, one per submit/dispatch — the engine's constant-size
        # "what just happened" buffer behind the queue-depth stats
        self._depths = deque(maxlen=int(depth_ring))
        # scalar accounting only: one (latency_s, queue_s, deadline_ms)
        # sample per completion — never the Request itself, whose payload
        # and result arrays belong to the caller
        self._samples = []
        self._first_enqueue_t = None
        self._last_complete_t = None
        self._dropped = 0
        self._dispatches = 0
        self._slots_dispatched = 0  # dispatched slots (rung-rounded on mesh)
        self._useful_rows = 0

    def warm_ladder(self, rungs=None):
        """Compile (and dispatch once, warming the jit call cache) every
        ladder rung's inference program before traffic arrives — the
        serving counterpart of ``TrainingSession.warm_run``: without it the
        first requests to hit each rung pay its compile inside their
        latency, and a load run's percentiles measure XLA, not serving."""
        S_rows = self._slot_rows
        in_dim = self._session.spec.sizes[0]
        for rung in rungs if rungs is not None else self._ladder:
            self._session.predict(np.zeros((rung * S_rows, in_dim), np.float32))

    # -- queue --------------------------------------------------------------

    @property
    def queue_depth(self):
        return len(self._queue)

    def _record_depth(self, t):
        self._depths.append((t, len(self._queue)))
        self._metrics.gauge("serving.queue_depth", len(self._queue))

    def submit(self, x, deadline_ms=None, arrival_t=None):
        """Enqueue one request of ``(rows, in_dim)`` inputs; returns its
        ``Request``. ``arrival_t`` backdates the enqueue timestamp to the
        request's scheduled arrival (the open-loop driver uses it so
        latency counts from ARRIVAL, not from when a busy host got around
        to submitting — the coordinated-omission correction). A request
        larger than one dispatch (``max_slots`` slots) is refused; beyond
        ``max_queue`` it is dropped and returned with verdict "dropped"."""
        x = np.asarray(x, np.float32)
        if x.ndim != 2 or x.shape[0] < 1:
            raise ValueError(f"request must be (rows >= 1, in_dim), got {x.shape}")
        n_slots = serving_slots.slots_needed(x.shape[0], self._slot_rows)
        if n_slots > self._max_slots:
            raise ValueError(
                f"request of {x.shape[0]} rows needs {n_slots} slots — more "
                f"than one dispatch ({self._max_slots} slots); split it"
            )
        # coerce to a plain float: a numpy scalar arrival (e.g. straight
        # from poisson_arrivals) would otherwise poison every downstream
        # timestamp and fail the strict-JSON metrics sink
        t = self.clock() if arrival_t is None else float(arrival_t)
        req = Request(self._next_id, x, n_slots, deadline_ms, t)
        self._next_id += 1
        if self._max_queue is not None and len(self._queue) >= self._max_queue:
            req.verdict = "dropped"
            self._dropped += 1
            self._record_request(req)
            return req
        self._queue.append(req)
        self._record_depth(t if arrival_t is None else self.clock())
        return req

    # -- continuous batching ------------------------------------------------

    def step(self):
        """Pack the queue's head into the next inference dispatch and run
        it; returns the completed requests ([] when the queue is empty).

        Packing is FIFO and slot-granular: requests join until the next
        one would overflow ``max_slots``, the packed slot count is rounded
        up the ladder, and every request's rows land in its OWN slots —
        which is why each response is bitwise-equal to a direct
        ``predict()`` of the same rows."""
        if not self._queue:
            return []
        t_d = self.clock()
        batch, used = [], 0
        while self._queue:
            head = self._queue[0]
            if batch and used + head.slots > self._max_slots:
                break
            self._queue.popleft()
            head.dispatch_t = t_d
            batch.append(head)
            used += head.slots
        rung = serving_slots.rung_for(used, self._ladder)
        S_rows = self._slot_rows
        flat = np.concatenate(
            [
                np.pad(r.x, ((0, r.slots * S_rows - r.rows), (0, 0)))
                for r in batch
            ],
            axis=0,
        )
        # the session pads the tail up to the rung and dispatches the
        # cached rung program — the same call path a direct predict() takes
        preds = self._session.predict(flat)
        t_c = self.clock()
        off = 0
        for r in batch:
            r.result = preds[off : off + r.rows]
            off += r.slots * S_rows
            r.complete_t = t_c
            r.verdict = "ok"
            self._record_request(r)
            self._samples.append((r.latency_s, r.queue_s, r.deadline_ms))
            if self._first_enqueue_t is None or r.enqueue_t < self._first_enqueue_t:
                self._first_enqueue_t = r.enqueue_t
            if self._last_complete_t is None or t_c > self._last_complete_t:
                self._last_complete_t = t_c
        self._dispatches += 1
        # mesh dispatches pay the rung program's full slot count; a
        # sequential dispatch runs exactly the occupied slots
        self._slots_dispatched += used if self._sequential else rung
        self._useful_rows += sum(r.rows for r in batch)
        self._record_depth(t_c)
        return batch

    def drain(self):
        """Serve until the queue is empty; returns everything completed."""
        done = []
        while self._queue:
            done.extend(self.step())
        return done

    def _record_request(self, req):
        self._metrics.request(
            req.verdict,
            id=req.id,
            rows=req.rows,
            slots=req.slots,
            enqueue_ts=req.enqueue_t,
            dispatch_ts=req.dispatch_t,
            complete_ts=req.complete_t,
            latency_s=req.latency_s,
            queue_s=req.queue_s,
            deadline_ms=req.deadline_ms,
            slo_ok=req.slo_ok(self._slo_ms),
        )

    # -- accounting ---------------------------------------------------------

    def stats(self):
        """Aggregate accounting over everything served since the last
        ``reset_stats()`` — the field set of the schema-v5 ``serving``
        summary record (all plain scalars, folded from the per-completion
        scalar samples; no served payload is retained)."""
        lats = [lat for lat, _, _ in self._samples]
        queues = [q for _, q, _ in self._samples]
        # per-request deadline tag wins over the engine SLO; with neither,
        # the verdict is None — Request.slo_ok's exact semantics
        slo_flags = []
        for lat, _, dl in self._samples:
            bound = dl if dl is not None else self._slo_ms
            slo_flags.append(
                None if bound is None or lat is None else lat <= bound / 1000.0
            )
        window = None
        if self._samples:
            window = float(self._last_complete_t - self._first_enqueue_t)
        padded_rows = self._slots_dispatched * self._slot_rows
        depths = [d for _, d in self._depths]
        met = sum(1 for ok in slo_flags if ok)
        return {
            "completed": len(self._samples),
            "dropped": self._dropped,
            "dispatches": self._dispatches,
            "slots_dispatched": self._slots_dispatched,
            "useful_rows": self._useful_rows,
            "padding_waste": (
                1.0 - self._useful_rows / padded_rows if padded_rows else None
            ),
            "p50_latency_s": _pct(lats, 50),
            "p99_latency_s": _pct(lats, 99),
            "max_latency_s": max(lats) if lats else None,
            "mean_queue_s": (sum(queues) / len(queues)) if queues else None,
            "window_s": window,
            "achieved_rps": (
                len(self._samples) / window if window else None
            ),
            # goodput: completions that met their deadline/SLO, per second
            # of the serving window (None when no threshold exists — an
            # unmeasured goodput must not read as a perfect one)
            "goodput_rps": (
                met / window
                if window and any(ok is not None for ok in slo_flags)
                else None
            ),
            "slo_ms": self._slo_ms,
            "slo_met": met if any(ok is not None for ok in slo_flags) else None,
            "queue_depth_max": max(depths) if depths else 0,
            "queue_depth_mean": (
                sum(depths) / len(depths) if depths else 0.0
            ),
        }

    def record_summary(self, offered_rps=None, name="summary"):
        """Emit (and return) the schema-v5 ``serving`` summary record:
        ``stats()`` plus the offered load and the analytical latency floor
        (``costmodel.serving_latency_bound`` — ticks x per-tick cost)."""
        rec = self.stats()
        rec["offered_rps"] = offered_rps
        rec["slot_rows"] = self._slot_rows
        rec["max_slots"] = self._max_slots
        bound = self._session.inference_latency_bound()
        rec["latency_bound_s"] = bound["seconds"]
        rec["latency_bound_ticks"] = bound["ticks"]
        rec["latency_bound_source"] = bound["peak_source"]
        self._metrics.serving(name, **rec)
        return rec

    def reset_stats(self):
        """Clear the accounting (the bench sweep's per-rate boundary);
        queued requests are unaffected."""
        self._samples = []
        self._first_enqueue_t = None
        self._last_complete_t = None
        self._depths.clear()
        self._dropped = 0
        self._dispatches = 0
        self._slots_dispatched = 0
        self._useful_rows = 0


def _pct(values, q):
    values = [v for v in values if v is not None]
    if not values:
        return None
    return float(np.percentile(np.asarray(values, np.float64), q))
