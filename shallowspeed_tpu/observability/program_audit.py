"""XLA program audit: collective & memory introspection + a comms cost model.

The paper's correctness story is layout-invariance — seq, DP and pipeline
runs must be the *same computation* rearranged — but a FLOP model alone
(costmodel.py) never verifies what XLA actually compiled. This module owns
the compiled-program evidence:

- ``parse_collectives`` / ``collective_census``: parse ``Compiled.as_text()``
  (post-optimization HLO) and count the collective ops by kind — all-reduce,
  all-gather, reduce-scatter, collective-permute, all-to-all (async
  ``-start`` forms count once; their ``-done`` halves are skipped) — with
  per-op result-shape byte sizes. HLO holds each ``lax.scan`` body ONCE
  regardless of trip count, so the census is STRUCTURAL: it answers "which
  collectives exist in the program" (the layout contract), not "how many
  dynamic executions happen" (that is the analytical model's job below);
- ``memory_stats``: ``Compiled.memory_analysis()`` pulled through one shared
  helper (scripts/tpu_capture.py and bench.py use the same path) — argument
  / output / temp / alias split plus a ``peak_hbm_bytes`` estimate;
- ``expected_comms``: the ANALYTICAL comms contract derived from the layout
  spec and the lowered tick tables (``lowering.program_comm_bytes``) —
  which collective kinds the layout requires/forbids, and the bytes each
  device moves per optimizer step per mesh axis (dp ring all-reduce of the
  gradient, 2 ppermutes x relay width x ticks for the pipeline,
  reduce-scatter + all-gather under ZeRO-1), with a bandwidth-bound
  lower-bound step time against the interconnect peak and a comms- vs
  compute-bound verdict;
- ``check_census`` / ``verify_census``: the cross-check that FAILS LOUDLY
  (``AuditMismatchError``) when the compiled program's collective census
  disagrees with the layout's contract — "the DP all-reduce really is one
  psum" as a tested invariant, not prose;
- ``audit_compiled``: the full audit record (schema-v3 ``xla_audit`` kind;
  docs/observability.md) a ``TrainingSession`` emits at jit time.

Census contract semantics (why kinds, not exact op counts): XLA lowers a
pytree psum into one all-reduce per leaf (or fuses several into one
variadic op), version-dependently; loss psums, pmax replication and the
norm reductions add more. Exact all-reduce counts are therefore compiler
noise, but the KIND set is the layout's signature: a sequential program
must contain no collectives at all, a pipeline (pp > 1) program must
relay through collective-permutes (one per direction, so >= 2; at pp == 1
the executor's permutes are device-local self-loops — allowed in the
census, never demanded nor counted as interconnect traffic), dp > 1
without ZeRO-1 must all-reduce and must NOT reduce-scatter/all-gather,
and ZeRO-1 must reduce-scatter AND all-gather (even at dp=1 — the
chunked update always lowers both).

BUCKETED gradient sync (``grad_bucket_bytes > 0``, parallel/gradsync.py)
tightens the contract beyond kinds: each bucket is deliberately emitted
as ONE flat collective, so every planned bucket must be ACCOUNTED FOR by
the compiled sync ops — one op of exactly the bucket's result-byte size,
or one op whose size is the sum of a merged run of ADJACENT buckets
(backend collective-combiner passes may fuse neighboring small
collectives; a merged program still syncs every planned byte and must
not be refused). A tampered plan fails the match, as does the common
unwired-knob shape on this jax (the legacy DP anchor lowers one
all-reduce per LEAF, whose sizes cannot be partitioned into the planned
bucket sums). Known evidence limit: ONE sync op of the total byte size
is accepted — a combiner that merged every bucket and an unwired ZeRO-1
anchor (one flat reduce-scatter) are byte-identical in the census, and
refusing would abort healthy combiner-merged runs; wiring regressions
of that shape are instead pinned by the CPU census tests, where no
combiner runs and the per-bucket ops are visible individually
(tests/test_program_audit.py::test_compiled_census_matches_bucket_plan).
Total synced bytes are unchanged by bucketing; only the op granularity
moves, which is exactly what this accounting pins down.
"""

import math
import os
import re

from shallowspeed_tpu.observability.costmodel import (
    mlp_train_flops_per_sample,
    peak_flops_per_chip,
)

# Collective HLO op names, in the spelling ``Compiled.as_text()`` uses.
COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

# Per-chip HBM capacity by platform: the v5e datasheet figure for TPU
# (16 GiB HBM2), a clearly-labeled NOMINAL figure for host CPU (there is no
# single honest "device memory" for a host; the source tag says so).
# Override with SHALLOWSPEED_HBM_BYTES for any other hardware.
HBM_PER_CHIP = {
    "tpu": 16 * 2**30,
    "cpu": 8 * 2**30,
}

# Per-chip interconnect bandwidth (bytes/s) by platform: the v5e datasheet
# aggregate ICI figure (1600 Gbps = 200 GB/s per chip), and a NOMINAL
# loopback figure for emulated host-CPU meshes (collectives there are
# memcpys; the tag says nominal). Override with SHALLOWSPEED_PEAK_BW_BYTES.
INTERCONNECT_BYTES_PER_SEC = {
    "tpu": 200e9,
    "cpu": 10e9,
}

ENV_HBM = "SHALLOWSPEED_HBM_BYTES"
ENV_BW = "SHALLOWSPEED_PEAK_BW_BYTES"

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# one HLO shape token: dtype[dims] with an optional layout suffix
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")

# a collective instruction: "<lhs> = <result-type> <kind>[-start|-done](..."
# The result type is either one shape or a tuple of shapes; matching it
# before the op name keeps metadata op_name strings (later on the line)
# from ever matching. The tuple alternative must tolerate ONE level of
# nested parentheses: TPU post-optimization HLO writes tiled layouts like
# ``(f32[8,128]{1,0:T(8,128)}, ...)`` and async collectives return tuples,
# so a paren-naive tuple match would silently drop exactly the ops the
# audit exists to see.
_COLLECTIVE_RE = re.compile(
    r"=\s*(?P<rtype>\((?:[^()]|\([^()]*\))*\)|[a-z][a-z0-9]*\[[0-9,]*\]\S*)\s*"
    r"(?P<kind>" + "|".join(COLLECTIVE_KINDS) + r")"
    r"(?P<phase>-start|-done)?(?:\.\d+)?\("
)


class AuditMismatchError(ValueError):
    """The compiled program's collective census violates the layout's
    analytical contract — either the lowering or the contract regressed."""


# the HLO module header's donation evidence: ``input_output_alias={ {0}:
# (0, {}, may-alias), {1,0}: (2, {1}, must-alias), ... }`` — each entry
# maps an output (tuple) index to the (parameter number, parameter tuple
# index, alias kind) whose buffer it reuses. jit's donate_argnums is what
# puts entries here; a program with NO donation has no such clause.
_ALIAS_MARKER = "input_output_alias={"
_ALIAS_ENTRY_RE = re.compile(
    r"\{(?P<out>[0-9,\s]*)\}:\s*\(\s*(?P<param>\d+)\s*,\s*"
    r"\{(?P<pidx>[0-9,\s]*)\}\s*(?:,\s*(?P<kind>[a-z_-]+)\s*)?\)"
)


def _alias_block(hlo_text):
    """The brace-balanced body of the module header's
    ``input_output_alias={...}`` clause, or None when the program
    declares no aliasing. Brace-scanned, not regexed: the body nests
    one brace level per tuple index and a paren-naive match would
    truncate exactly the entries this pass exists to see."""
    start = hlo_text.find(_ALIAS_MARKER)
    if start < 0:
        return None
    i = start + len(_ALIAS_MARKER)
    depth = 1
    for j in range(i, min(len(hlo_text), i + 100_000)):
        c = hlo_text[j]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
            if depth == 0:
                return hlo_text[i:j]
    return hlo_text[i:]  # unterminated header: parse what is there


def parse_input_output_aliases(hlo_text):
    """Every input/output buffer alias the compiled program declares, as
    ``{"output_index", "param_number", "param_index", "kind"}`` dicts
    (``kind`` is ``may-alias``/``must-alias``; empty list = the whole
    parameter/output, not a tuple leaf). An empty list means the program
    donates nothing — the property the dispatch-safety pass proves."""
    body = _alias_block(hlo_text)
    if body is None:
        return []
    out = []
    for m in _ALIAS_ENTRY_RE.finditer(body):
        out.append(
            {
                "output_index": [
                    int(v) for v in m.group("out").split(",") if v.strip()
                ],
                "param_number": int(m.group("param")),
                "param_index": [
                    int(v) for v in m.group("pidx").split(",") if v.strip()
                ],
                "kind": m.group("kind") or "may-alias",
            }
        )
    return out


def donation_census(hlo_text):
    """Aggregate donation evidence for one program: alias entry count,
    the distinct donated parameter numbers, and the per-kind split —
    the field set the ``static_analysis``/``xla_audit`` records carry."""
    aliases = parse_input_output_aliases(hlo_text)
    kinds = {}
    for a in aliases:
        kinds[a["kind"]] = kinds.get(a["kind"], 0) + 1
    return {
        "aliased_outputs": len(aliases),
        "donated_params": sorted({a["param_number"] for a in aliases}),
        "kinds": kinds,
    }


def check_dispatch_safety(hlo_text, context="compiled program"):
    """The dispatch-safety leg: a program that will be DISPATCHED from a
    deserialized (AOT-cache) executable, or that serves requests, must
    not donate its buffers — executing a deserialized donating program
    is the jax-0.4.x heap-corruption hazard PR 1 hit (conftest's
    segfault gate), and a serving program's params are reused by the
    very next dispatch, so donation there is a use-after-free by
    construction (serving/engine.py). Returns a list of human-readable
    mismatch strings (empty = dispatch-safe)."""
    census = donation_census(hlo_text)
    if not census["aliased_outputs"]:
        return []
    return [
        f"{context}: program donates its input buffers "
        f"(input_output_alias: {census['aliased_outputs']} aliased "
        f"output(s) over params {census['donated_params']}, kinds "
        f"{census['kinds']}) — dispatching it from a deserialized "
        "executable or a serving path is the documented use-after-free "
        "hazard (docs/static-analysis.md, docs/robustness.md)"
    ]


def verify_dispatch_safety(compiled_or_text, context="compiled program"):
    """``check_dispatch_safety`` that fails loudly (AuditMismatchError,
    unlatched like the census — a caught-and-retried caller re-verifies
    and re-raises). Accepts a ``Compiled`` object or its ``as_text()``
    dump; returns the donation census record on a pass. A backend that
    exposes no HLO text yields ``None`` — no evidence, recorded as
    unverifiable, never a silent pass/fail."""
    text = compiled_or_text
    if not isinstance(text, str):
        try:
            text = compiled_or_text.as_text()
        except Exception:  # noqa: BLE001 — backend-optional surface
            text = None
    if text is None:
        return None
    mismatches = check_dispatch_safety(text, context=context)
    if mismatches:
        raise AuditMismatchError("; ".join(mismatches))
    return donation_census(text)


def _shape_bytes_each(type_str):
    """Byte size of every shape token in an HLO type (a shape, or a tuple
    of shapes), in order. Unknown dtypes count 0 bytes — the census must
    never crash on exotic types; the op is still counted."""
    sizes = []
    for dtype, dims in _SHAPE_RE.findall(type_str):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        sizes.append(n * _DTYPE_BYTES.get(dtype, 0))
    return sizes


def _shape_bytes(type_str, async_start=False):
    """Byte size of one HLO result type. Async ``-start`` ops return a
    tuple pairing the ALIASED operands with the results — ``(op_0..op_k,
    res_0..res_k)`` — so counting the whole tuple would double the op's
    real payload; for an even-length start tuple only the result half is
    summed (exact for same-shape in/out collectives like all-reduce and
    collective-permute, and the honest half for all-gather where the
    result leg IS the payload). Odd/unrecognized tuples fall back to the
    full sum."""
    sizes = _shape_bytes_each(type_str)
    if async_start and len(sizes) >= 2 and len(sizes) % 2 == 0:
        sizes = sizes[len(sizes) // 2:]
    return sum(sizes)


def parse_collectives(hlo_text):
    """All collective instructions in a post-optimization HLO dump.

    Returns a list of ``{"kind", "bytes"}`` dicts — ``kind`` uses
    underscores (``all_reduce``) for JSON-friendliness, ``bytes`` is the
    op's RESULT-shape size (what each participating device holds after the
    op; algorithmic wire bytes are the analytical model's concern). Async
    pairs count once: the ``-start`` op carries the collective, its
    ``-done`` half is skipped, and the start tuple's operand-alias legs
    are excluded from the byte count (see ``_shape_bytes``).
    """
    ops = []
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if not m or m.group("phase") == "-done":
            continue
        ops.append(
            {
                "kind": m.group("kind").replace("-", "_"),
                "bytes": _shape_bytes(
                    m.group("rtype"), async_start=m.group("phase") == "-start"
                ),
            }
        )
    return ops


def census_of_ops(ops):
    """Aggregate a ``parse_collectives`` op list into the census shape:
    ``{kind: {"count": n, "bytes": summed result bytes}}``."""
    census = {}
    for op in ops:
        agg = census.setdefault(op["kind"], {"count": 0, "bytes": 0})
        agg["count"] += 1
        agg["bytes"] += op["bytes"]
    return census


def collective_census(hlo_text):
    """-> ``{kind: {"count": n, "bytes": summed result bytes}}``."""
    return census_of_ops(parse_collectives(hlo_text))


def memory_stats(compiled):
    """``Compiled.memory_analysis()`` as a plain dict — the ONE shared path
    (TrainingSession audits, scripts/tpu_capture.py's VMEM calibration and
    bench.py's published record all read through here, so their byte
    accounting can never disagree).

    Fields (whichever the backend reports): ``argument_size_in_bytes``,
    ``output_size_in_bytes``, ``temp_size_in_bytes``,
    ``alias_size_in_bytes``, ``generated_code_size_in_bytes``, plus
    ``peak_hbm_bytes`` — the backend's explicit peak when it exposes one,
    else the live-buffer estimate ``arguments + outputs + temp - aliased``
    (donated buffers are counted once). All sizes are PER DEVICE: XLA's
    memory analysis reports the addressable shard (verified empirically —
    an argument sharded over N devices reports 1/N of its global bytes),
    so ``peak_hbm_bytes`` compares directly against one chip's capacity.
    Returns ``None`` when the backend offers nothing: memory analysis is
    evidence, never a hard dependency.
    """
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — backend-optional surface
        return None
    if ma is None:
        return None
    out = {}
    for field in (
        "argument_size_in_bytes",
        "output_size_in_bytes",
        "temp_size_in_bytes",
        "alias_size_in_bytes",
        "generated_code_size_in_bytes",
    ):
        v = getattr(ma, field, None)
        if v is not None:
            out[field] = int(v)
    peak = getattr(ma, "peak_memory_in_bytes", None)
    if peak:
        out["peak_hbm_bytes"] = int(peak)
    elif out:
        out["peak_hbm_bytes"] = (
            out.get("argument_size_in_bytes", 0)
            + out.get("output_size_in_bytes", 0)
            + out.get("temp_size_in_bytes", 0)
            - out.get("alias_size_in_bytes", 0)
        )
    return out or None


def hbm_per_chip(platform):
    """-> ``(capacity_bytes, source)`` for one chip; ``(None, source)``
    when the platform is unknown. Same provenance discipline as
    ``costmodel.peak_flops_per_chip``: a nominal figure is tagged nominal."""
    env = os.environ.get(ENV_HBM)
    if env:
        return float(env), f"env:{ENV_HBM}"
    plat = "tpu" if platform in ("tpu", "axon") else platform
    if plat not in HBM_PER_CHIP:
        return None, f"unknown-platform:{platform}"
    source = "datasheet-v5e-hbm" if plat == "tpu" else "nominal-cpu-default"
    return HBM_PER_CHIP[plat], source


def interconnect_bytes_per_sec(platform):
    """-> ``(bytes_per_sec, source)`` per chip; ``(None, source)`` when
    unknown. TPU: the v5e aggregate ICI figure; CPU: a nominal loopback
    figure (emulated-mesh collectives are memcpys); env override for DCN
    or anything else."""
    env = os.environ.get(ENV_BW)
    if env:
        return float(env), f"env:{ENV_BW}"
    plat = "tpu" if platform in ("tpu", "axon") else platform
    if plat not in INTERCONNECT_BYTES_PER_SEC:
        return None, f"unknown-platform:{platform}"
    source = "datasheet-v5e-ici" if plat == "tpu" else "nominal-cpu-default"
    return INTERCONNECT_BYTES_PER_SEC[plat], source


def zero_peak_forecast(spec, dp, pp, tp=1, state_parts=0, num_chunks=None,
                       bucketed=False):
    """The analytical per-device PARAM-STATE footprint at every ZeRO
    stage — the structural model behind the OOM-forecast headroom claim
    ("params + grads + state ÷ dp"), priced from the SAME layout math the
    executor shards with (``gradsync.stacked_flat_len`` /
    ``zero_block_slots``), so the forecast and the emitters can never
    disagree about a shard's bytes.

    Per stage: ``params_bytes`` (at rest), ``grads_bytes`` (the persistent
    gradient residency — full slabs at stages 0-1, the reduce-scattered
    shard at 2-3), ``state_bytes`` (``state_parts`` optimizer parts, full
    or sharded), ``transient_bytes`` (stage 3 only: one chunk's gathered
    params live inside a tick), and their ``total_bytes``.

    ``bucketed=True`` prices the overlap variant of stage 2 honestly: a
    ``grad_bucket_bytes`` plan keeps the FULL-slab accumulators through
    the scan (that is what makes its tail reduce-scatter bitwise-equal to
    zero-1 at any microbatch count), so the bucketed stage-2 gradient
    residency is the full ``f``, not the shard — only the anchor's
    per-tick scatter into the persistent shard carry earns the ÷dp row. All figures are
    f32 model-state bytes per device — activations, mailboxes and XLA
    temps ride on top, so the measured ``peak_hbm_bytes`` exceeds the
    forecast by a (stage-independent) activation floor; what the forecast
    prices is the DELTA between stages, which is what the bench
    scoreboard verifies against measurements."""
    from shallowspeed_tpu.parallel.executor import (
        stacked_flat_len,
        zero_block_slots,
    )

    f = 4 * stacked_flat_len(spec, pp, tp)  # per-device stacked f32 bytes
    _, csz3 = zero_block_slots(spec, pp, dp, tp)
    shard = 4 * csz3  # the padded block-cyclic per-rank shard
    n = int(state_parts)
    chunks = int(num_chunks) if num_chunks else 1
    # string stage keys: the record round-trips through JSON (json turns
    # int keys into strings anyway — be the same shape before and after)
    stages = {
        "0": {"params_bytes": f, "grads_bytes": f, "state_bytes": n * f,
              "transient_bytes": 0},
        "1": {"params_bytes": f, "grads_bytes": f, "state_bytes": n * shard,
              "transient_bytes": 0},
        "2": {"params_bytes": f,
              "grads_bytes": f if bucketed else shard,
              "state_bytes": n * shard, "transient_bytes": 0},
        "3": {"params_bytes": shard, "grads_bytes": shard,
              "state_bytes": n * shard,
              # JIT gathering keeps ONE chunk's params live at a time
              "transient_bytes": -(-f // chunks)},
    }
    for s in stages.values():
        s["total_bytes"] = (
            s["params_bytes"] + s["grads_bytes"] + s["state_bytes"]
            + s["transient_bytes"]
        )
    return {
        "stacked_param_bytes_per_device": f,
        "shard_bytes_per_device": shard,
        "state_parts": n,
        "stages": stages,
    }


def expected_comms(
    spec,
    dp,
    pp,
    prog=None,
    zero1=False,
    zero=None,
    mubatch_size=None,
    platform="cpu",
    precision="highest",
    grad_bucket_plan=None,
    tp=1,
    opt_state_parts=0,
):
    """The layout's analytical comms contract, derived from the model spec
    and (on mesh layouts) the LOWERED tick tables — the numbers the
    compiled program is audited against, and the comms section of the run
    report.

    ``prog`` may be a TRAINING tick program (the default contract below) or
    an INFERENCE one (``prog.is_training`` False — the serving engine's
    compiled predict programs): inference keeps the pp-relay leg but
    forbids the ZeRO collectives outright, and pins ``all_reduce`` at AT
    MOST ONE op — the lawful preds psum (which survives compilation even
    at pp=1, measured) — so a serving program that lowers a gradient-sync
    reduce-scatter/all-gather, or a SECOND all-reduce beyond the preds
    psum, fails its audit before the first request is served.

    Returns a JSON-able dict:

    - ``required`` / ``forbidden``: collective kinds the layout's contract
      demands present / absent (see the module docstring for the
      semantics; ``check_census`` enforces them);
    - ``axes``: per-mesh-axis expected traffic, bytes PER DEVICE PER
      OPTIMIZER STEP (one global batch):

      * ``pp`` (pp > 1 only — at pp == 1 the executor's permutes are
        device-local self-loops, not interconnect traffic): 2 ppermutes
        (one per direction) every tick, payload
        ``mubatch_size x relay_width`` f32 — wire bytes are
        ``2 * ticks * payload`` from the ACTUAL tick tables
        (``lowering.program_comm_bytes``), so masked no-op ticks are
        counted (the SPMD program really ships their zero payloads), and
        the useful (send-table) bytes ride alongside;
      * ``dp`` (no zero1): the gradient psum as a ring all-reduce —
        ``2 * (dp-1)/dp x grad_bytes`` where ``grad_bytes`` is this
        device's PADDED stacked gradient (slot stacks x 4 bytes);
      * ``dp`` (zero1): reduce-scatter + all-gather of the padded flat
        param vector, ``2 * (dp-1)/dp x flat_bytes``;

      the dp axis entry comes from ``gradsync.sync_comm_bytes`` and
      carries the sync ``mode`` — with a ``grad_bucket_plan`` it also
      carries the bucketed contract (``num_buckets`` + per-bucket
      grad/census bytes; total bytes unchanged) that ``check_census``
      verifies against the compiled ops;

      * ``tp`` (tp > 1 only): the Megatron all-reduces — one psum over
        'tp' per row-parallel slot forward (plus the closing gather when
        the last slot is column-parallel) and one per column-parallel
        slot backward, i.e. 2 per layer pair per fwd+bwd pass. Site
        widths come from ``executor.tp_allreduce_sites`` (the REAL
        tp-rounded activation shapes), the per-step dynamic bytes from
        the tick program's cell counts (every (device, chunk) stage runs
        M microbatch passes per step), and ``hlo_min_all_reduce_ops`` is
        the STRUCTURAL floor ``check_census`` enforces: the compiled
        program must hold at least that many all-reduce ops (each psum
        site is a distinct op inside its tick branch; the dp sync, loss
        and norm reductions only add more). The tp gradient sync is
        deliberately absent — TP shards the weights, so the dp axis
        already moves 1/tp per device and no extra gradient collective
        exists over tp;

    - ``bytes_per_step_per_device``: the axes' total;
    - ``comms_time_per_step_s``: bandwidth-bound lower bound at the
      platform's interconnect peak (with provenance);
    - ``compute_time_per_step_s``: per-device padded-FLOP lower bound at
      the platform's matmul peak (``costmodel.peak_flops_per_chip``);
    - ``bound``: ``"comms"`` / ``"compute"`` — which lower bound dominates
      (None when either peak is unknown);
    - ``serial_bound_s`` / ``overlapped_bound_s``: the two step-time lower
      bounds — ``comm + compute`` prices the legacy anchor (no gradient
      communication can start until the whole backward ends, nothing
      overlaps), ``max(comm, compute)`` prices perfectly-overlapped
      bucketed sync; their gap is the overlap headroom the bucketing knob
      exists to claim, and ``model_hidden_comm_share`` (``min(comm,
      compute) / comm``) is the share of communication a perfect overlap
      hides — the model-side number next to the MEASURED overlap
      efficiency the report derives from a trace's comm/compute split.
    """
    if zero is None:
        zero = 1 if zero1 else 0
    zero = int(zero)
    sequential = prog is None
    axes = {}
    required, forbidden = [], []
    if sequential:
        # one device, one program: ANY collective is a contract violation
        forbidden = [k.replace("-", "_") for k in COLLECTIVE_KINDS]
        flops_per_step = mlp_train_flops_per_sample(spec.sizes) * spec.global_batch_size
    else:
        from shallowspeed_tpu.parallel.lowering import (
            program_comm_bytes,
            program_flops,
        )

        forbidden.append("all_to_all")
        inference = not prog.is_training
        if tp > 1:
            # the Megatron axis: its all-reduces exist in BOTH training and
            # inference programs (forward row-slot psums survive either
            # way), so the kind is required and a structural op-count floor
            # rides the axis entry for check_census
            from shallowspeed_tpu.parallel.executor import tp_allreduce_sites

            fwd_w, bwd_w = tp_allreduce_sites(spec, tp, training=not inference)
            cells = prog.num_chunks * prog.num_micro_batches
            # activation recompute re-runs the whole stage forward inside
            # the backward tick: every forward psum site fires TWICE per
            # (chunk, microbatch) — the comms side of the recompute tax —
            # and the OP_RECOMPUTE switch branch holds its own copy of the
            # forward psum ops, raising the structural op-count floor
            rec = bool(getattr(prog, "recompute", False))
            fwd_passes = 2 if rec else 1
            payload = 4 * mubatch_size * cells * (
                fwd_passes * sum(fwd_w) + sum(bwd_w)
            )
            axes["tp"] = {
                "kind": "all_reduce",
                "algorithm": "ring",
                "sites_fwd": len(fwd_w),
                "sites_bwd": len(bwd_w),
                "site_payload_bytes": [
                    4 * mubatch_size * w for w in list(fwd_w) + list(bwd_w)
                ],
                "allreduce_bytes_per_device": int(payload),
                "bytes_per_step_per_device": int(2 * (tp - 1) / tp * payload),
                "hlo_min_all_reduce_ops": (
                    fwd_passes * len(fwd_w) + len(bwd_w)
                ),
            }
            required.append("all_reduce")
        if pp > 1:
            # only a real pipeline axis demands the relay permutes; at
            # pp == 1 the executor still emits them, but as SELF-LOOPS —
            # present in the census (allowed), zero interconnect traffic
            # (an on-device copy must not inflate the bandwidth bound)
            required.append("collective_permute")
            comm = program_comm_bytes(prog, spec, mubatch_size)
            # the executor emits BOTH directions every tick, but an
            # inference program never reads its backward mailbox, so XLA
            # dead-code-eliminates that whole direction (observed on the
            # compiled census: exactly one permute survives) — the wire
            # model and the census rule both count one direction
            wire = comm["wire_bytes_per_device"]
            useful = comm["useful_bytes_per_device"]
            if inference:
                wire //= 2
            axes["pp"] = {
                "kind": "collective_permute",
                "ticks": comm["num_ticks"],
                "payload_bytes": comm["relay_payload_bytes"],
                "bytes_per_step_per_device": wire,
                "useful_bytes_per_step_per_device": useful,
            }
        if inference:
            # inference/serving program: a forward-only relay plus ONE
            # lawful reduction — the head stage's predictions are
            # psum-replicated over pp (executor: `lax.psum(preds, "pp")`;
            # non-head devices contribute zeros), required at pp > 1 and
            # allowed-but-degenerate at pp == 1. The ZeRO collectives are
            # training-only: a reduce-scatter or all-gather in a serving
            # program means the training lowering leaked into the
            # inference path.
            forbidden += ["reduce_scatter", "all_gather"]
            if pp > 1:
                required.append("all_reduce")
                from shallowspeed_tpu.parallel.executor import slot_shapes

                # the executor psums the PADDED head width — tp-rounded
                # when a tp axis is active (slot dims round to tp
                # multiples), so the contract sizes what really moves
                preds_bytes = (
                    4
                    * prog.num_micro_batches
                    * mubatch_size
                    * slot_shapes(spec, tp)[-1][0]
                )
                axes["preds"] = {
                    "kind": "all_reduce",
                    "bytes_per_step_per_device": int(
                        2 * (pp - 1) / pp * preds_bytes
                    ),
                }
        else:
            from shallowspeed_tpu.parallel.gradsync import sync_comm_bytes

            if zero >= 1:
                # every sharded stage lowers both collectives, dp=1
                # included: stages 1-2 in the tail (reduce-scatter the
                # grads / shards, all-gather the updated chunk), stage 3
                # per tick (reduce-scatter into the grad-shard carry,
                # all-gather the layer params just in time)
                required += ["reduce_scatter", "all_gather"]
            else:
                forbidden += ["reduce_scatter", "all_gather"]
                if dp > 1:
                    # "the DP all-reduce really is one psum" (or one per
                    # bucket): the kind must be there (leaf-count fusion
                    # makes exact UNBUCKETED op counts compiler noise — see
                    # the module docstring; the bucketed contract pins
                    # counts)
                    required.append("all_reduce")
            # the dp-axis byte model (anchor, per-bucket, or the stage-3
            # per-tick schedule) has ONE definition, shared with the
            # executor's emitters: gradsync.sync_comm_bytes. Stage 3's
            # gather traffic scales with the microbatch passes — recompute
            # re-gathers the layer params inside the backward tick, a
            # third pass per (chunk, microbatch)
            axes["dp"] = sync_comm_bytes(
                spec, dp, pp, zero=zero, plan=grad_bucket_plan, tp=tp,
                mubatches=prog.num_micro_batches,
                gather_passes=(
                    3 if getattr(prog, "recompute", False) else 2
                ),
            )
        # per-device padded compute: the tick program's FLOPs are the whole
        # pp x tp group's; SPMD uniformity (and the Megatron shards) split
        # them evenly across devices
        flops_per_step = program_flops(prog, spec, mubatch_size, tp=tp) / (pp * tp)

    # a kind may be demanded by several axes (dp sync + tp psums are both
    # all-reduce); the contract lists it once
    required = list(dict.fromkeys(required))
    total = sum(a["bytes_per_step_per_device"] for a in axes.values())
    bw, bw_source = interconnect_bytes_per_sec(platform)
    peak, peak_source = peak_flops_per_chip(platform, precision)
    comms_t = (total / bw) if bw else None
    compute_t = (flops_per_step / peak) if peak else None
    bound = None
    serial_t = overlapped_t = hidden_share = None
    if comms_t is not None and compute_t is not None:
        bound = "comms" if comms_t > compute_t else "compute"
        # the two step-time lower bounds: the anchor's serial comm-then-
        # compute chain vs the perfectly-overlapped bucketed sync
        serial_t = comms_t + compute_t
        overlapped_t = max(comms_t, compute_t)
        if comms_t > 0:
            hidden_share = min(comms_t, compute_t) / comms_t
    forecast = None
    if not sequential and prog.is_training:
        forecast = zero_peak_forecast(
            spec, dp, pp, tp=tp, state_parts=opt_state_parts,
            num_chunks=prog.num_chunks,
            bucketed=bool(grad_bucket_plan) and int(zero or 0) == 2,
        )
    return {
        "dp": int(dp),
        "pp": int(pp),
        "tp": int(tp),
        "zero": zero,
        "zero1": zero == 1,
        "zero_forecast": forecast,
        "sequential": sequential,
        "inference": bool(prog is not None and not prog.is_training),
        "required": required,
        "forbidden": forbidden,
        "axes": axes,
        "bytes_per_step_per_device": total,
        "bandwidth_bytes_per_sec": bw,
        "bandwidth_source": bw_source,
        "comms_time_per_step_s": comms_t,
        "compute_flops_per_step_per_device": flops_per_step,
        "peak_flops_per_chip": peak,
        "peak_flops_source": peak_source,
        "compute_time_per_step_s": compute_t,
        "bound": bound,
        "serial_bound_s": serial_t,
        "overlapped_bound_s": overlapped_t,
        "model_hidden_comm_share": hidden_share,
    }


def check_census(census, expected, ops=None):
    """Compare a compiled program's collective census against the layout
    contract. Returns a list of human-readable mismatch strings (empty =
    the census matches).

    ``ops`` (optional): the per-op list from ``parse_collectives`` — when
    the contract's dp axis is BUCKETED, the bucket-accounting check runs
    against it (every planned bucket matched by a sync op of its exact
    result size or by a combiner-merged adjacent run's sum — see the
    module docstring; without ``ops`` there is no per-op size evidence
    and only the kind legs run).
    """
    mismatches = []
    for kind in expected.get("required", ()):
        if census.get(kind, {}).get("count", 0) < 1:
            mismatches.append(
                f"required collective {kind!r} is absent from the compiled "
                f"program (census: {sorted(census) or 'empty'})"
            )
    for kind in expected.get("forbidden", ()):
        n = census.get(kind, {}).get("count", 0)
        if n:
            mismatches.append(
                f"forbidden collective {kind!r} appears {n}x in the "
                "compiled program"
            )
    if "collective_permute" in expected.get("required", ()):
        n = census.get("collective_permute", {}).get("count", 0)
        # inference programs relay ONE direction (the backward mailbox is
        # dead code and XLA eliminates its permute), so the both-directions
        # rule applies to training programs only
        if 0 < n < 2 and not expected.get("inference"):
            mismatches.append(
                "pipeline relay must permute in BOTH directions "
                f"(>= 2 collective-permutes); compiled program has {n}"
            )
    tp_axis = (expected.get("axes") or {}).get("tp") or {}
    if expected.get("inference") and not tp_axis:
        # a forward-only program has exactly one lawful all-reduce — the
        # preds psum over pp (it survives compilation even at pp=1,
        # measured on the CPU backend) — so a second one means a
        # gradient-sync collective leaked into the serving path. Zero is
        # tolerated: a backend MAY elide the degenerate psum, and the
        # required-kinds leg above still demands it at pp > 1. At tp > 1
        # this exact pin is replaced by the tp-axis floor below (the
        # Megatron row-slot psums are lawful forward all-reduces); the
        # reduce-scatter/all-gather prohibition still catches a leaked
        # ZeRO gradient sync there.
        n = census.get("all_reduce", {}).get("count", 0)
        if n > 1:
            mismatches.append(
                "forward-only inference program must lower at most ONE "
                f"all-reduce (the preds psum); compiled program has {n} — "
                "a gradient sync leaked into the serving path"
            )
    if tp_axis:
        # the Megatron structural floor: each tp psum site is a distinct
        # all-reduce op inside its tick branch (HLO holds branch bodies
        # once); dp sync / loss / norm reductions only ADD ops, so a
        # census below the floor means the tp lowering dropped collectives
        need = int(tp_axis.get("hlo_min_all_reduce_ops", 0))
        n = census.get("all_reduce", {}).get("count", 0)
        if n < need:
            mismatches.append(
                f"tensor-parallel program must hold >= {need} all-reduce "
                f"ops ({tp_axis.get('sites_fwd')} forward + "
                f"{tp_axis.get('sites_bwd')} backward Megatron psum sites); "
                f"compiled program has {n}"
            )
        if expected.get("inference") and n > need + 1:
            # the forward-only UPPER pin survives tp: the lawful ops are
            # exactly the Megatron sites plus the one preds psum (the tp
            # psums form a dependency chain over distinct replica groups,
            # so no combiner can merge them) — anything beyond reads as a
            # leaked gradient all-reduce, same class the tp=1 at-most-one
            # pin catches
            mismatches.append(
                f"forward-only tensor-parallel program must lower at most "
                f"{need + 1} all-reduce ops ({need} Megatron sites + the "
                f"preds psum); compiled program has {n} — a gradient sync "
                "leaked into the serving path"
            )
    dp_axis = (expected.get("axes") or {}).get("dp") or {}
    need_ag = int(dp_axis.get("hlo_min_all_gather_ops", 0))
    if need_ag and expected.get("dp", 1) > 1:
        # the ZeRO-3 JIT-gather structural floor: every gather-bearing
        # tick branch (forward, backward, recompute) holds its own
        # all-gather ops in HLO (branch bodies lower once), and the tail
        # adds none — a census below the floor means a gather-bearing
        # branch lowered without its parameter gather
        n = census.get("all_gather", {}).get("count", 0)
        if n < need_ag:
            mismatches.append(
                f"zero-3 program must hold >= {need_ag} all-gather ops "
                "(one JIT parameter gather per gather-bearing tick "
                f"branch); compiled program has {n}"
            )
    mismatches += _check_bucketed_sync(census, expected, ops)
    return mismatches


def _check_bucketed_sync(census, expected, ops):
    """The bucketed gradient-sync leg of the contract: the emitters
    deliberately lower one flat collective per bucket, so every planned
    bucket must be accounted for by the compiled sync ops — one op of
    exactly the bucket's result size, or one op of a MERGED adjacent
    run's summed size (backend collective combiners may fuse neighboring
    small collectives; a merged program still syncs every planned byte
    and must not be refused). A tampered plan fails; so does the
    per-leaf unwired-anchor shape — but a SINGLE op of the total size is
    accepted (indistinguishable from a full combiner merge; see the
    module docstring for where that regression shape is pinned instead).
    Checked only with per-op evidence (``ops``) and only when the
    dp axis is real traffic (dp > 1 — at dp == 1 XLA may elide the
    degenerate collectives entirely, which is not a lowering bug)."""
    axis = (expected.get("axes") or {}).get("dp") or {}
    if axis.get("mode") != "bucketed" or expected.get("dp", 1) <= 1:
        return []
    if ops is None:
        return []  # census aggregates carry no per-op sizes: no evidence
    # stages 1-2 both bucket their tail reduce-scatter (stage 3 has no
    # plan: plan_buckets refuses); stage 0 buckets the anchor all-reduce
    stage = expected.get("zero", 1 if expected.get("zero1") else 0)
    kind = "reduce_scatter" if stage else "all_reduce"
    planned = [int(b) for b in axis.get("bucket_census_bytes", ())]
    compiled = sorted(op["bytes"] for op in ops if op["kind"] == kind)
    if _buckets_accounted(planned, compiled):
        return []

    def _fmt(sizes):
        s = ", ".join(str(v) for v in sizes[:12])
        return f"[{s}{', ...' if len(sizes) > 12 else ''}]"

    return [
        f"bucketed sync: the compiled program's {kind} result sizes "
        f"{_fmt(compiled)} cannot account for the planned bucket sizes "
        f"{_fmt(planned)} (neither one op per bucket nor merged adjacent "
        "runs)"
    ]


def _buckets_accounted(planned, compiled, node_budget=100_000):
    """Can the ordered ``planned`` bucket sizes be partitioned into
    contiguous runs whose sums each match a distinct ``compiled`` op
    size? Run length 1 everywhere is the exact one-op-per-bucket case;
    longer runs are combiner-merged neighbors (combiners fuse ops
    adjacent in the schedule, i.e. consecutive buckets). Extra compiled
    ops (loss psums, norm reductions) may go unused. Backtracking with a
    node budget; when the search is infeasible (pathological many-equal-
    size plans, or a plan deeper than Python's recursion limit) fall back
    to the weaker total-bytes check rather than refusing a healthy
    program on solver timeout."""
    from collections import Counter

    class _Exhausted(Exception):
        pass

    avail = Counter(compiled)
    budget = [node_budget]

    def match(i):
        if budget[0] <= 0:
            raise _Exhausted  # budget spent: no verdict either way
        budget[0] -= 1
        if i == len(planned):
            return True
        run = 0
        for j in range(i, len(planned)):
            run += planned[j]
            if avail[run] > 0:
                avail[run] -= 1
                if match(j + 1):
                    return True
                avail[run] += 1
        return False

    try:
        return match(0)
    except (_Exhausted, RecursionError):
        return sum(compiled) >= sum(planned)


def verify_census(census, expected, context="compiled program", ops=None):
    """``check_census`` that fails loudly — the tested layout invariant.
    Pass ``ops`` (the ``parse_collectives`` list) to enforce the bucketed
    size-accounting leg too; without it only the kind legs can fire."""
    mismatches = check_census(census, expected, ops=ops)
    if mismatches:
        raise AuditMismatchError(
            f"{context}: collective census disagrees with the layout "
            "contract: " + "; ".join(mismatches)
        )


def audit_compiled(compiled, expected=None, platform=None, n_devices=1):
    """The full jit-time audit of one compiled program: collective census +
    memory analysis (+ the contract verdict when ``expected`` is given) —
    the field set of the schema-v3 ``xla_audit`` record.

    ``platform`` adds the HBM-capacity leg: ``memory_stats`` sizes are
    PER DEVICE (see its docstring), so ``peak_hbm_bytes`` is compared
    against one chip's capacity directly — no sharding approximation
    (``hbm_source`` carries the capacity's provenance, same honesty rule
    as the MFU peak).
    """
    try:
        text = compiled.as_text()
    except Exception:  # noqa: BLE001 — backend-optional surface
        text = None
    ops = parse_collectives(text) if text else []
    census = census_of_ops(ops)
    rec = {
        "hlo_available": text is not None,
        "census": census,
        "memory": memory_stats(compiled),
        "n_devices": int(n_devices),
    }
    if platform is not None:
        cap, src = hbm_per_chip(platform)
        rec["platform"] = platform
        rec["hbm_per_chip"] = cap
        rec["hbm_source"] = src
        mem = rec["memory"]
        if cap and mem and mem.get("peak_hbm_bytes") is not None:
            rec["peak_hbm_per_chip_bytes"] = mem["peak_hbm_bytes"]
            rec["hbm_headroom_fraction"] = 1.0 - mem["peak_hbm_bytes"] / cap
    if expected is not None:
        mismatches = check_census(census, expected, ops=ops) if text else []
        rec["expected"] = expected
        rec["mismatches"] = mismatches
        # no HLO text -> nothing to audit; None, not a silent pass/fail
        rec["census_ok"] = (not mismatches) if text else None
    return rec


def format_bytes(n):
    """Human-readable byte count (shared by the report renderer)."""
    if n is None or not isinstance(n, (int, float)) or not math.isfinite(n):
        return "n/a"
    for unit, div in (("GiB", 2**30), ("MiB", 2**20), ("KiB", 2**10)):
        if abs(n) >= div:
            return f"{n / div:,.2f} {unit}"
    return f"{n:,.0f} B"
