# Convenience targets. The CPU_MESH prefix runs any layout on 8 emulated
# devices (and keeps the TPU tunnel plugin out of CPU-only processes).
CPU_MESH = env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
           XLA_FLAGS=--xla_force_host_platform_device_count=8

.PHONY: test data train train-mesh bench bench-scaling schedules clean

test:
	python -m pytest tests/ -q

data:
	python prepare_data.py

train:
	python train.py --epochs 5

train-mesh:
	$(CPU_MESH) python train.py --dp 2 --pp 4 --schedule gpipe --epochs 2

bench:
	python bench.py

bench-scaling:
	$(CPU_MESH) python scripts/bench_scaling.py

bench-matrix:
	python scripts/bench_tpu_matrix.py

# one-shot full TPU measurement (baseline, unroll sweeps at both precision
# classes, interleaved matrix + full-epoch pallas/xla cells, convergence,
# profiler trace) — run when the chip is healthy
tpu-capture:
	python scripts/tpu_capture.py

# bank only the tier-0 verdict cells (headline pair + kernel ladder +
# equality probes) — for a chip window too short for the full matrix
tpu-capture-tier0:
	python scripts/tpu_capture.py --tier0-only

# unattended: probe the tunnel every 10 min, run the resumable capture on
# the first healthy probe (see scripts/tunnel_watch.sh)
tpu-watch:
	bash scripts/tunnel_watch.sh

# the convergence-equivalence experiment behind the default-precision
# bench headline (20-epoch run at --precision default + same-window pair)
tpu-default-precision:
	python scripts/tpu_default_precision.py

schedules:
	$(CPU_MESH) python scripts/show_schedule.py --all

clean:
	rm -rf .pytest_cache */__pycache__ __pycache__ tests/__pycache__
