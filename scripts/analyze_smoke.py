"""analyze-smoke driver: prove the static-analysis layer end-to-end
(`make analyze-smoke`; docs/static-analysis.md).

Two phases:

  clean    every training layout (seq, dp2, gpipe-pp4, zero1-dp2xpp2) is
           constructed with --audit semantics (audit=True + JSONL
           metrics) and trained one epoch, plus one serving rung
           dispatched on the pipeline layout. Asserts: the lowering-time
           static passes (send/recv match, MPMD deadlock-freedom, stash
           lifetime) ran GREEN on every lowered program BEFORE first
           dispatch (schema-v9 static_analysis records, findings == 0),
           the collective census stayed clean, and the serving rung's
           compiled HLO passed the donation dispatch-safety check
           (which runs refusing-before-dispatch on the serving path).
           Sequential layouts lower no tick program — the audit census
           covers them and the record set says so honestly.

  violate  one deliberately-broken program per check class, each
           asserted REFUSED with the offending tick/evidence named:
           an unmatched send and a leaked stash slot (tampered gpipe
           tick tables), a cyclic wait (synthetic 2-stage
           mutual-recv program), and a donating executable pushed at
           the dispatch-safety pass (a real jit donate_argnums compile).

Usage:
  python scripts/analyze_smoke.py --phase clean --data-dir D --out-dir O
  python scripts/analyze_smoke.py --phase violate
"""

import argparse
import dataclasses
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

LADDER = (1, 2, 4)

LAYOUTS = {
    "seq": {},
    "dp2": {"dp": 2, "mubatches": 2},
    "pp4": {"pp": 4, "schedule": "gpipe", "mubatches": 4,
            "predict_slot_ladder": LADDER},
    "zero1": {"dp": 2, "pp": 2, "schedule": "gpipe", "zero1": True,
              "mubatches": 2},
}


def phase_clean(args):
    from shallowspeed_tpu.api import TrainingSession
    from shallowspeed_tpu.observability import JsonlMetrics, read_jsonl

    fail = []
    for name, kw in LAYOUTS.items():
        out = Path(args.out_dir) / f"{name}.jsonl"
        metrics = JsonlMetrics(out)
        session = TrainingSession(
            global_batch_size=32, data_dir=args.data_dir, metrics=metrics,
            audit=True, **kw,
        )
        session.train_run(1, with_eval=False)
        if name == "pp4":
            # the whole serving rung ladder through the audited dispatch
            # path: per-rung static passes + forward-only census +
            # donation dispatch-safety, each BEFORE its first dispatch
            rng = np.random.RandomState(0)
            for rung in LADDER:
                session.predict(
                    rng.rand(
                        rung * session.slot_rows, session.spec.sizes[0]
                    ).astype(np.float32)
                )
        metrics.close()
        recs = read_jsonl(out)
        audits = [r for r in recs if r.get("kind") == "xla_audit"]
        if not audits or not all(r.get("census_ok") for r in audits):
            fail.append(f"{name}: collective census not clean")
        sa = [r for r in recs if r.get("kind") == "static_analysis"]
        if name == "seq":
            if sa:
                fail.append(f"{name}: unexpected static_analysis records "
                            "on a sequential layout (no tick program)")
            print(f"{name}: census clean (sequential — no tick program)")
            continue
        want = {"epoch_program"} | (
            {f"inference_r{r}" for r in LADDER} if name == "pp4" else set()
        )
        got = {r["name"] for r in sa}
        if not want <= got:
            fail.append(f"{name}: static_analysis records {sorted(got)} "
                        f"missing {sorted(want - got)}")
        if any(r.get("findings") for r in sa):
            fail.append(f"{name}: static analysis reported findings")
        if not all(
            set(r.get("passes", ())) >= {"send_recv", "deadlock", "stash"}
            for r in sa
        ):
            fail.append(f"{name}: a static_analysis record is missing a pass")
        print(
            f"{name}: static passes green on {sorted(got)} "
            "(send_recv, deadlock, stash), census clean"
        )
    if fail:
        print("analyze-smoke clean phase FAILED: " + "; ".join(fail),
              file=sys.stderr)
        return 1
    return 0


def phase_violate(_args):
    from shallowspeed_tpu import schedules as S
    from shallowspeed_tpu.analysis import (
        ProgramAnalysisError,
        check_deadlock_free,
        check_send_recv,
        check_stash_lifetime,
    )
    from shallowspeed_tpu.observability import program_audit
    from shallowspeed_tpu.parallel.lowering import OP_FWD, lower_schedule

    fail = []
    base = lower_schedule(S.GPipeSchedule, 4, 4)

    def expect_refusal(label, fn, err, needle):
        try:
            fn()
        except err as e:
            if needle in str(e):
                print(f"{label}: refused — {str(e)[:110]}")
                return
            fail.append(f"{label}: refusal does not name the evidence: {e}")
            return
        fail.append(f"{label}: deliberately broken program was NOT refused")

    # 1. unmatched send: drop the consuming read of a delivered message
    rf = np.array(base.read_fwd_slot)
    t, s = np.argwhere(rf != base.n_fwd_slots)[0]
    rf[t, s] = base.n_fwd_slots
    bad = dataclasses.replace(base, read_fwd_slot=rf)
    expect_refusal(
        "unmatched-send", lambda: check_send_recv(bad),
        ProgramAnalysisError, "tick",
    )

    # 2. leaked stash slot: drop a backward's stash free
    sr = np.array(base.stash_read)
    t, s = np.argwhere(sr != base.n_stash_slots)[-1]
    sr[t, s] = base.n_stash_slots
    expect_refusal(
        "stash-leak",
        lambda: check_stash_lifetime(dataclasses.replace(base, stash_read=sr)),
        ProgramAnalysisError, "leaked stash slot",
    )

    # 3. cyclic wait: two single-cell stages, each recv-ing the other's
    # send — the classic mutual-wait shape no lockstep tick can hide
    one = np.ones((1, 2), np.int32)
    zero = np.zeros((1, 2), np.int32)
    cyclic = dataclasses.replace(
        base,
        num_ticks=1, num_stages=2, num_micro_batches=1,
        n_fwd_slots=1, n_bwd_slots=1,
        op=np.full((1, 2), OP_FWD, np.int32), mb=zero,
        read_fwd_slot=np.array([[1, 0]], np.int32),
        read_bwd_slot=np.array([[0, 1]], np.int32),
        in_fwd_slot=np.array([[1, 0]], np.int32),
        in_bwd_slot=np.array([[0, 1]], np.int32),
        send_fwd=np.array([[1, 0]], np.int32),
        send_bwd=np.array([[0, 1]], np.int32),
        stash_write=one, stash_read=one, stash_peek=one,
        gstash_write=zero, gstash_read=zero,
        chunk=zero, load_in=zero, is_head=zero,
    )
    expect_refusal(
        "deadlock", lambda: check_deadlock_free(cyclic),
        ProgramAnalysisError, "cyclic wait",
    )

    # 4. donation: a REAL donating executable at the dispatch-safety pass
    import jax
    import jax.numpy as jnp

    donating = (
        jax.jit(lambda a, b: (a + b, a * b), donate_argnums=(0,))  # noqa: SSP004 — the deliberate violation this phase exists to inject
        .lower(jnp.zeros((8, 8)), jnp.ones((8, 8)))
        .compile()
    )
    expect_refusal(
        "donation",
        lambda: program_audit.verify_dispatch_safety(
            donating, context="injected"
        ),
        program_audit.AuditMismatchError, "input_output_alias",
    )

    if fail:
        print("analyze-smoke violate phase FAILED: " + "; ".join(fail),
              file=sys.stderr)
        return 1
    print("violate phase: all four injected violations refused before dispatch")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--phase", choices=["clean", "violate"], required=True)
    ap.add_argument("--data-dir")
    ap.add_argument("--out-dir")
    args = ap.parse_args(argv)
    if args.phase == "clean":
        if not (args.data_dir and args.out_dir):
            ap.error("--phase clean requires --data-dir and --out-dir")
        return phase_clean(args)
    return phase_violate(args)


if __name__ == "__main__":
    sys.exit(main())
