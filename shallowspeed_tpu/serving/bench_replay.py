"""The capacity scoreboard: one seeded diurnal trace replayed three
ways, scored against the offline oracle (ROADMAP item 4 / ISSUE 18).

    python -m shallowspeed_tpu.serving.bench_replay \\
        --data-dir /tmp/data --checkpoint ckpt.npz \\
        --knee-from sweep.json --out AUTOSCALE_r01.json

The three replays of the SAME ``serving/replay.py`` arrival schedule:

- **static**: a fixed fleet sized for the day's peak (the classic
  no-autoscaler provisioning — it pays for the peak all night and
  still drowns in the flash crowd),
- **autoscaled**: ``serving/autoscaler.py`` closing the loop, starting
  from ``min_replicas``,
- **chaos**: the autoscaled leg again with a replica SIGKILLed at the
  peak — the leg whose flap count must be ZERO (a kill answered by a
  replacement is recovery; a kill answered by scale-in/out churn is a
  policy bug).

The OFFLINE ORACLE is computed, not driven: from the recorded rate
trace and the measured knee, the per-bucket minimum feasible fleet
``clamp(ceil(rate / knee), min, max)`` — hindsight with zero reaction
lag. Buckets whose demand exceeds even ``max_replicas`` are marked
infeasible: violation minutes NO policy could have avoided.

SCORING (the two axes of the scoreboard, both vs the oracle):

- **SLO-violation minutes**: per trace bucket, the requests that
  ARRIVED in the bucket are folded into p99 latency + achieved-ok
  rate and judged by ``observability.slo.slo_breach`` — the SAME
  predicate ``bench_serving.find_knee`` uses, so the knee that sized
  the oracle and the scorer that judges the legs can never disagree.
  A breached bucket charges its full width. Backpressure refusals and
  deadline expiries lower the achieved rate, so shed load is charged
  honestly, never hidden.
- **wasted replica-hours**: the integral of ``max(0, fleet(t) -
  oracle(t))`` — capacity paid for that perfect hindsight would not
  have run. Under-provisioning is never credited here; it shows up as
  violations instead.

Both are reported in compressed wall units AND modeled-day units
(compressed x the trace's ``compression``), so "violation minutes" read
on the day the trace stands for.

Determinism (pinned by ``tests/test_replay.py``): every scoring
function in this module is pure — trace + samples + timeline in, the
same record out, byte for byte. Wall-clock enters only through the
driven legs; the committed ``AUTOSCALE_r01.json`` is therefore a
machine-specific artifact whose CAVEATS record the CPU-fallback
context, while its verdicts (autoscaled beats static on both axes,
zero chaos flaps) are the machine-checked gate.
"""

import argparse
import json
import math
import os
import sys

from shallowspeed_tpu.observability import slo
from shallowspeed_tpu.observability.metrics import json_safe
from shallowspeed_tpu.observability.stats import percentile
from shallowspeed_tpu.serving.autoscaler import AutoscalePolicy
from shallowspeed_tpu.serving.fleet import ServingFleet
from shallowspeed_tpu.serving.loadgen import (
    payload_in_dim,
    request_payloads,
    run_open_loop,
)
from shallowspeed_tpu.serving.replay import diurnal_trace

SCOREBOARD_VERSION = 1
SCOREBOARD_RECORD = "autoscale_scoreboard"


# -- the offline oracle ------------------------------------------------------


def oracle_schedule(buckets, knee_rps, min_replicas=1, max_replicas=4):
    """The hindsight-optimal replica schedule: per trace bucket, the
    minimum feasible fleet ``ceil(rate / knee)`` clamped to the same
    ``[min, max]`` the policy is allowed — the oracle must not be
    credited with fleets the mechanism could never run. ``infeasible``
    marks buckets whose demand exceeds ``max_replicas`` x knee: their
    width is violation time no schedule could avoid."""
    if knee_rps is None or knee_rps <= 0:
        raise ValueError("oracle needs the measured knee_rps")
    out = []
    for b in buckets:
        required = max(1, int(math.ceil(b["rate_rps"] / knee_rps)))
        out.append(
            {
                "t0": b["t0"],
                "t1": b["t1"],
                "rate_rps": b["rate_rps"],
                "required": required,
                "replicas": min(max(required, min_replicas), max_replicas),
                "infeasible": required > max_replicas,
            }
        )
    return out


def replica_timeline(n0, decisions):
    """The fleet-size step function ``[(t, n), ...]`` a leg ran:
    starting size plus every ``scale_out``/``scale_in`` decision's
    ``replicas_after`` at its decision time. Replacements and
    backpressure toggles don't change the paid-for size (a replacement
    swaps a dead process for a warming one), so they don't appear."""
    timeline = [(0.0, int(n0))]
    for d in decisions:
        if d.get("decision") in ("scale_out", "scale_in"):
            timeline.append((float(d["t"]), int(d["replicas_after"])))
    return timeline


def _segments(timeline, t_end):
    """The step function as closed segments ``[(t0, t1, n), ...]``
    covering ``[0, t_end]``."""
    segs = []
    for i, (t, n) in enumerate(timeline):
        t1 = timeline[i + 1][0] if i + 1 < len(timeline) else t_end
        if t1 > t:
            segs.append((t, min(t1, t_end), n))
    return segs


def replica_seconds(timeline, t_end):
    """Total replica-seconds a leg paid for over ``[0, t_end]``."""
    return sum((t1 - t0) * n for t0, t1, n in _segments(timeline, t_end))


def wasted_replica_seconds(timeline, oracle):
    """Replica-seconds above the oracle: ``integral max(0, fleet(t) -
    oracle(t)) dt``, exact over the piecewise-constant pair (breakpoints
    = oracle bucket edges x timeline steps)."""
    t_end = oracle[-1]["t1"] if oracle else 0.0
    wasted = 0.0
    for t0, t1, n in _segments(timeline, t_end):
        for b in oracle:
            lo, hi = max(t0, b["t0"]), min(t1, b["t1"])
            if hi > lo:
                wasted += max(0, n - b["replicas"]) * (hi - lo)
    return wasted


# -- the violation-minute scorer ---------------------------------------------


def score_samples(
    samples,
    buckets,
    slo_ms,
    achieved_fraction=slo.SLO_ACHIEVED_FRACTION,
):
    """Fold one leg's terminal request samples into per-bucket breach
    verdicts via the SHARED ``slo.slo_breach`` predicate.

    ``samples``: dicts with ``arrival`` (scheduled arrival, trace
    seconds), ``verdict``, ``latency_s`` (None unless ok). Requests are
    charged to the bucket they ARRIVED in — the offered load they were
    part of — with coordinated-omission-corrected latencies, so a
    backlog that drains late still breaches the buckets that caused it.
    Returns the per-bucket rows plus total violation seconds and the
    verdict tallies."""
    rows = []
    violation_s = 0.0
    verdicts = {}
    for s in samples:
        verdicts[s["verdict"]] = verdicts.get(s["verdict"], 0) + 1
    for b in buckets:
        width = b["t1"] - b["t0"]
        inb = [s for s in samples if b["t0"] <= s["arrival"] < b["t1"]]
        lats = [
            s["latency_s"]
            for s in inb
            if s["verdict"] == "ok" and s["latency_s"] is not None
        ]
        n_ok = sum(1 for s in inb if s["verdict"] == "ok")
        p99 = percentile(lats, 99)
        achieved = (n_ok / width) if width > 0 else 0.0
        breach = slo.slo_breach(
            p99,
            b["offered_rps"],
            achieved,
            slo_ms,
            achieved_fraction=achieved_fraction,
        )
        if breach:
            violation_s += width
        rows.append(
            {
                "t0": b["t0"],
                "t1": b["t1"],
                "offered_rps": b["offered_rps"],
                "arrived": len(inb),
                "ok": n_ok,
                "achieved_rps": achieved,
                "p99_latency_s": p99,
                "breach": breach,
            }
        )
    return {"buckets": rows, "violation_s": violation_s, "verdicts": verdicts}


def score_leg(samples, buckets, slo_ms, timeline, oracle, compression=1.0):
    """The full per-leg score: violation minutes (compressed and
    modeled-day) + replica-hours paid and wasted vs the oracle."""
    scored = score_samples(samples, buckets, slo_ms)
    t_end = buckets[-1]["t1"] if buckets else 0.0
    paid_s = replica_seconds(timeline, t_end)
    wasted_s = wasted_replica_seconds(timeline, oracle)
    return {
        **scored,
        "timeline": [{"t": t, "replicas": n} for t, n in timeline],
        "violation_minutes": scored["violation_s"] / 60.0,
        "violation_minutes_modeled": scored["violation_s"] * compression / 60.0,
        "replica_s": paid_s,
        "replica_hours_modeled": paid_s * compression / 3600.0,
        "wasted_replica_s": wasted_s,
        "wasted_replica_hours_modeled": wasted_s * compression / 3600.0,
    }


def oracle_score(oracle, compression=1.0):
    """The oracle's own row on the scoreboard: its replica-hours (the
    spend floor) and the infeasible violation time no policy avoids."""
    violation_s = sum(
        b["t1"] - b["t0"] for b in oracle if b["infeasible"]
    )
    paid_s = sum((b["t1"] - b["t0"]) * b["replicas"] for b in oracle)
    return {
        "buckets": oracle,
        "violation_s": violation_s,
        "violation_minutes": violation_s / 60.0,
        "violation_minutes_modeled": violation_s * compression / 60.0,
        "replica_s": paid_s,
        "replica_hours_modeled": paid_s * compression / 3600.0,
        "wasted_replica_s": 0.0,
        "wasted_replica_hours_modeled": 0.0,
    }


def scoreboard_record(trace, knee_rps, slo_ms, legs, oracle, config=None,
                      caveats=()):
    """Assemble the versioned scoreboard record — pure and
    deterministic: the same inputs produce the same record byte for
    byte (no wall clocks in here; ``tests/test_replay.py`` pins it).
    ``legs`` maps leg name -> ``score_leg`` output (plus any extras the
    runner attached); verdicts compare autoscaled vs static on both
    axes and check the chaos leg's flap count."""
    compression = trace["config"]["compression"]
    verdicts = {}
    if "static" in legs and "autoscaled" in legs:
        verdicts["autoscaled_beats_static_violation_minutes"] = (
            legs["autoscaled"]["violation_s"] < legs["static"]["violation_s"]
        )
        verdicts["autoscaled_beats_static_wasted_replica_hours"] = (
            legs["autoscaled"]["wasted_replica_s"]
            < legs["static"]["wasted_replica_s"]
        )
    if "chaos" in legs:
        verdicts["chaos_zero_flaps"] = legs["chaos"].get("flaps", 0) == 0
    return {
        "bench": SCOREBOARD_RECORD,
        "bench_version": SCOREBOARD_VERSION,
        "config": {
            "knee_rps": knee_rps,
            "slo_ms": slo_ms,
            "trace": trace["config"],
            **(config or {}),
        },
        "trace_buckets": trace["buckets"],
        "compression": compression,
        "oracle": oracle_score(oracle, compression=compression),
        "legs": legs,
        "verdicts": verdicts,
        "caveats": list(caveats),
    }


# -- the driven legs ---------------------------------------------------------


def run_replay_leg(
    worker_config,
    in_dim,
    trace,
    n_replicas,
    slo_ms,
    deadline_ms=None,
    knee_rps=None,
    metrics=None,
    policy_kwargs=None,
    autoscale=False,
    kill_at=None,
    leg="static",
    seed=0,
    rows_choices=(1, 2, 3, 4, 8),
    fleet_retry=2,
):
    """Drive the trace through one fleet configuration; returns
    ``(samples, extras)`` where ``samples`` feed ``score_leg`` and
    ``extras`` carry the leg's fleet stats, decisions, flaps and kill
    evidence. The kill (``kill_at``, trace seconds) SIGKILLs the
    busiest ready replica once — the chaos leg's injected death."""
    arrivals = trace["arrivals"]
    payloads = request_payloads(
        len(arrivals), in_dim, seed=seed, rows_choices=rows_choices
    )
    policy = None
    if autoscale:
        policy = AutoscalePolicy(
            knee_rps=knee_rps,
            metrics=metrics,
            slo_ms=slo_ms,
            tags={"leg": leg},
            **(policy_kwargs or {}),
        )
    fleet = ServingFleet(
        worker_config,
        n_replicas=n_replicas,
        slo_ms=slo_ms,
        retry=fleet_retry,
        metrics=metrics,
        seed=seed,
        knee_rps=knee_rps if autoscale else None,
        alert_sinks=(policy,) if policy is not None else (),
    )
    kill = {"t": None, "replica": None}
    try:
        fleet.start()
        if policy is not None:
            policy.attach(fleet)

        def on_tick(now):
            if kill_at is not None and kill["t"] is None and now >= kill_at:
                ready = [
                    info
                    for info in fleet.replicas.values()
                    if info.state == "ready"
                ]
                if ready:
                    victim = max(
                        ready, key=lambda r: (r.inflight, -r.replica_id)
                    )
                    kill["t"] = now
                    kill["replica"] = victim.replica_id
                    fleet.sigkill_replica(victim.replica_id)
            if policy is not None:
                policy.tick(now)

        t0 = fleet.clock()
        done = run_open_loop(
            fleet,
            payloads,
            arrivals,
            deadline_ms=deadline_ms,
            on_tick=on_tick if (policy is not None or kill_at is not None)
            else None,
        )
        stats = fleet.stats()
    finally:
        fleet.stop()
    samples = [
        {
            "arrival": r.enqueue_t - t0,
            "t": None if r.complete_t is None else r.complete_t - t0,
            "verdict": r.verdict,
            "latency_s": r.latency_s,
        }
        for r in done
    ]
    extras = {
        "leg": leg,
        "n_replicas_start": n_replicas,
        "stats_summary": {
            k: stats.get(k)
            for k in (
                "completed", "dropped", "expired", "errors", "unhealthy",
                "availability", "p50_latency_s", "p99_latency_s",
                "failovers", "failover_requeued", "scale_ups", "scale_downs",
                "replicas_dead", "replicas_retired", "degraded",
            )
        },
        "gate_dropped": stats.get("gate_dropped"),
        "decisions": list(policy.decisions) if policy is not None else [],
        "flaps": policy.flaps if policy is not None else 0,
        "backpressure_events": (
            sum(
                1
                for d in (policy.decisions if policy is not None else [])
                if d["decision"] == "backpressure_on"
            )
        ),
        "kill_t": kill["t"],
        "killed_replica": kill["replica"],
    }
    return samples, extras


# -- CLI ---------------------------------------------------------------------


def _knee_from_sweep(path):
    with open(path, encoding="utf-8") as f:
        record = json.load(f)
    knee = record.get("knee_rps")
    if knee is None:
        raise SystemExit(
            f"{path}: sweep record has no knee (knee_rps null) — sweep "
            f"higher rates; the scoreboard needs a measured knee"
        )
    slo_ms = record.get("slo_ms")
    if slo_ms is None:
        slo_ms = (record.get("config") or {}).get("slo_ms")
    return float(knee), slo_ms


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="capacity scoreboard: diurnal replay x "
        "{static, autoscaled, chaos} vs the offline oracle"
    )
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--checkpoint", default=None)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--pp", type=int, default=1)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument(
        "--schedule",
        choices=["naive", "gpipe", "pipedream", "interleaved"],
        default="gpipe",
    )
    ap.add_argument("--global-batch-size", type=int, default=8)
    ap.add_argument("--mubatches", type=int, default=1)
    ap.add_argument("--aot-cache", default=None, metavar="DIR")
    ap.add_argument("--max-slots", type=int, default=None)
    ap.add_argument(
        "--dispatch-floor-ms",
        type=float,
        default=0.0,
        help="per-dispatch service-time floor for every replica worker "
        "(engine.py 'dispatch floor'): on a CPU testbed it makes a "
        "replica's capacity slot-concurrency-bound so fleet capacity "
        "scales with replica count; pass the SAME value the knee sweep "
        "was measured with",
    )
    ap.add_argument("--reload-dir", default=None)
    ap.add_argument(
        "--knee-from",
        default=None,
        metavar="SWEEP_JSON",
        help="read the measured knee_rps (and slo_ms default) from a "
        "bench_serving sweep record — the measurement-before-mechanism "
        "path",
    )
    ap.add_argument(
        "--knee-rps",
        type=float,
        default=None,
        help="explicit knee override (recorded as a caveat: the "
        "scoreboard prefers the measured sweep)",
    )
    ap.add_argument("--slo-ms", type=float, default=None)
    ap.add_argument("--deadline-ms", type=float, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--rows", default="1,2,3,4,8")
    ap.add_argument(
        "--day-s",
        type=float,
        default=90.0,
        help="compressed day length in wall seconds (the trace records "
        "the compression factor vs a real 24h day)",
    )
    ap.add_argument(
        "--base-frac",
        type=float,
        default=0.35,
        help="trough demand as a fraction of the measured knee",
    )
    ap.add_argument(
        "--peak-frac",
        type=float,
        default=1.4,
        help="diurnal peak demand as a fraction of the knee",
    )
    ap.add_argument("--spike-mult", type=float, default=2.0)
    ap.add_argument("--n-spikes", type=int, default=1)
    ap.add_argument("--bucket-s", type=float, default=None,
                    help="rate-trace bucket width (default day_s/30)")
    ap.add_argument("--min-replicas", type=int, default=1)
    ap.add_argument("--max-replicas", type=int, default=3)
    ap.add_argument(
        "--static-replicas",
        type=int,
        default=None,
        help="static leg size (default: peak-sized — "
        "clamp(ceil(peak demand / knee)))",
    )
    ap.add_argument(
        "--kill-at-frac",
        type=float,
        default=0.55,
        help="chaos leg: SIGKILL the busiest replica at this fraction "
        "of the day",
    )
    ap.add_argument(
        "--skip-chaos", action="store_true",
        help="score static vs autoscaled only (no kill leg)",
    )
    ap.add_argument("--out", default=None,
                    help="write AUTOSCALE_r01.json here")
    ap.add_argument(
        "--metrics-out",
        default=None,
        help="JSONL sink: autoscale decisions + request/rollup/alert "
        "streams for all legs (the report CLI's Capacity evidence)",
    )
    args = ap.parse_args(argv)

    from shallowspeed_tpu.observability import JsonlMetrics

    caveats = []
    if args.knee_from:
        knee_rps, sweep_slo = _knee_from_sweep(args.knee_from)
        if args.slo_ms is None:
            args.slo_ms = sweep_slo
    elif args.knee_rps:
        knee_rps = args.knee_rps
        caveats.append(
            "knee_rps passed by hand (--knee-rps), not measured by a "
            "bench_serving sweep on this machine"
        )
    else:
        raise SystemExit("need --knee-from SWEEP_JSON or --knee-rps")
    if args.slo_ms is None:
        raise SystemExit("need --slo-ms (or a sweep record that carries it)")
    if os.environ.get("JAX_PLATFORMS", "cpu") == "cpu":
        caveats.append(
            "CPU fallback: replica workers run the JAX CPU backend — "
            "absolute rates/latencies are machine-specific; the "
            "scoreboard's comparisons (static vs autoscaled vs oracle) "
            "replay the identical seeded trace, which is what the "
            "verdicts gate on"
        )
    if args.dispatch_floor_ms:
        caveats.append(
            f"dispatch_floor_ms={args.dispatch_floor_ms:g}: replica "
            "service time is padded to a fixed floor (engine.py "
            "'dispatch floor') so per-replica capacity is "
            "slot-concurrency-bound and fleet capacity scales with "
            "replica count even on a single-core host; on accelerators "
            "the model forward provides this floor natively"
        )

    metrics = JsonlMetrics(args.metrics_out) if args.metrics_out else None
    rows_choices = tuple(int(r) for r in args.rows.split(",") if r.strip())
    trace = diurnal_trace(
        day_s=args.day_s,
        base_rps=args.base_frac * knee_rps,
        peak_rps=args.peak_frac * knee_rps,
        seed=args.seed,
        n_spikes=args.n_spikes,
        spike_mult=args.spike_mult,
        bucket_s=args.bucket_s if args.bucket_s else args.day_s / 30.0,
    )
    oracle = oracle_schedule(
        trace["buckets"], knee_rps,
        min_replicas=args.min_replicas, max_replicas=args.max_replicas,
    )
    static_n = args.static_replicas
    if static_n is None:
        static_n = min(
            max(int(math.ceil(args.peak_frac)), args.min_replicas),
            args.max_replicas,
        )
    if metrics is not None:
        metrics.event(
            "replay_trace",
            seed=args.seed,
            day_s=args.day_s,
            knee_rps=knee_rps,
            n_arrivals=trace["config"]["n_arrivals"],
            compression=trace["config"]["compression"],
            buckets=[
                {"t0": b["t0"], "t1": b["t1"], "rate_rps": b["rate_rps"],
                 "offered_rps": b["offered_rps"]}
                for b in trace["buckets"]
            ],
            spikes=trace["config"]["spikes"],
        )

    worker_config = {
        "session": dict(
            dp=args.dp,
            pp=args.pp,
            tp=args.tp,
            schedule=args.schedule,
            global_batch_size=args.global_batch_size,
            mubatches=args.mubatches,
            data_dir=args.data_dir,
            resume=args.checkpoint,
            aot_cache_dir=args.aot_cache,
        ),
        "engine": dict(
            max_slots=args.max_slots,
            slo_ms=args.slo_ms,
            reload_dir=args.reload_dir,
            dispatch_floor_ms=args.dispatch_floor_ms,
        ),
    }
    in_dim = payload_in_dim(args.data_dir)
    # policy cadences scaled to the compressed day: eager out, slow in
    # (the hysteresis), flap window under the scale-in cooldown so a
    # cooldown-respecting reversal is legitimate, not a flap
    policy_kwargs = dict(
        min_replicas=args.min_replicas,
        max_replicas=args.max_replicas,
        out_cooldown_s=args.day_s / 45.0,
        in_cooldown_s=args.day_s / 10.0,
        slack_hold_s=args.day_s / 20.0,
        slack_fraction=0.6,
        flap_window_s=args.day_s / 12.0,
        floor_s=(
            args.dispatch_floor_ms / 1000.0
            if args.dispatch_floor_ms
            else None
        ),
    )
    compression = trace["config"]["compression"]

    leg_specs = [
        ("static", dict(n_replicas=static_n, autoscale=False)),
        (
            "autoscaled",
            dict(n_replicas=args.min_replicas, autoscale=True,
                 policy_kwargs=policy_kwargs),
        ),
    ]
    if not args.skip_chaos:
        leg_specs.append(
            (
                "chaos",
                dict(
                    n_replicas=args.min_replicas,
                    autoscale=True,
                    policy_kwargs=policy_kwargs,
                    kill_at=args.kill_at_frac * args.day_s,
                ),
            )
        )
    legs = {}
    for leg, kw in leg_specs:
        print(f"replaying leg {leg!r} ({trace['config']['n_arrivals']} "
              f"arrivals over {args.day_s:g}s)...")
        samples, extras = run_replay_leg(
            worker_config,
            in_dim,
            trace,
            slo_ms=args.slo_ms,
            deadline_ms=args.deadline_ms,
            knee_rps=knee_rps,
            metrics=metrics,
            seed=args.seed,
            rows_choices=rows_choices,
            leg=leg,
            **kw,
        )
        timeline = replica_timeline(
            kw["n_replicas"], extras["decisions"]
        )
        legs[leg] = {
            **score_leg(
                samples, trace["buckets"], args.slo_ms, timeline, oracle,
                compression=compression,
            ),
            **extras,
        }
        if metrics is not None:
            metrics.event(
                "replay_score",
                leg=leg,
                violation_s=legs[leg]["violation_s"],
                violation_minutes_modeled=legs[leg][
                    "violation_minutes_modeled"
                ],
                wasted_replica_s=legs[leg]["wasted_replica_s"],
                wasted_replica_hours_modeled=legs[leg][
                    "wasted_replica_hours_modeled"
                ],
                flaps=legs[leg]["flaps"],
            )

    record = scoreboard_record(
        trace,
        knee_rps,
        args.slo_ms,
        legs,
        oracle,
        config={
            "knee_source": args.knee_from or "--knee-rps",
            "seed": args.seed,
            "deadline_ms": args.deadline_ms,
            "dispatch_floor_ms": args.dispatch_floor_ms,
            "max_slots": args.max_slots,
            "min_replicas": args.min_replicas,
            "max_replicas": args.max_replicas,
            "static_replicas": static_n,
            "policy": policy_kwargs,
            "kill_at_s": (
                None if args.skip_chaos else args.kill_at_frac * args.day_s
            ),
        },
        caveats=caveats,
    )
    # the driven legs' sample rows stay out of the committed artifact
    # (they are per-machine noise); the per-bucket verdicts remain
    text = json.dumps(json_safe(record), indent=2, allow_nan=False)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text + "\n")
        print(f"capacity scoreboard written: {args.out}")
    else:
        print(text)
    for leg in legs:
        print(
            f"  {leg}: {legs[leg]['violation_minutes_modeled']:.0f} modeled "
            f"violation-min, {legs[leg]['wasted_replica_hours_modeled']:.1f} "
            f"wasted replica-h, {legs[leg]['flaps']} flap(s), "
            f"{len(legs[leg]['decisions'])} decision(s)"
        )
    print(
        f"  oracle: "
        f"{record['oracle']['violation_minutes_modeled']:.0f} modeled "
        f"violation-min (infeasible demand), "
        f"{record['oracle']['replica_hours_modeled']:.1f} replica-h floor"
    )
    if metrics is not None:
        metrics.close()
        print(f"telemetry written: {metrics.path} (+ .r* replica shards)")
    failures = [
        name for name, ok in record["verdicts"].items() if not ok
    ]
    if failures:
        print("capacity scoreboard FAILED: " + ", ".join(failures),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
