"""One-shot TPU measurement capture: everything BASELINE.md needs, one claim.

The axon TPU tunnel is single-client and historically fragile, so when it IS
healthy we capture every number in one process/one device claim:

  1. NumPy reference baseline (host CPU — the denominator, bench.py protocol);
  2. headline: fused sequential epoch throughput, scan-unroll sweep, at both
     DEFAULT precision (the convergence-verified bench headline config) and
     fp32 HIGHEST (the bitwise-NumPy-parity config) — each sweep's cells
     measured with interleaved trials (same-window comparisons);
  3. 20-epoch flagship convergence on the prepared dataset, with per-epoch
     validation accuracy (end-to-end wall time, final accuracy, model hash);
  4. a jax.profiler trace of one post-compile epoch (artifacts/tpu_trace/);
  5. the single-chip tuning matrix (fusion x precision x pallas backend) and
     full-epoch fused pallas-vs-xla cells, interleaved — the pallas cells
     compile for real on the chip (non-interpret mode);
  6. adam kernel cells + a 1-epoch adam convergence through the epoch
     kernel.

TIER-0 FIRST (round-4 verdict #1): before any of the long phases, a minimal
bundle — NumPy denominator, the fused default/highest headline pair at the
default unroll, and the sgd kernel LADDER (xla/mega/epoch/run) WITH its
on-chip equality probes — is measured and banked as its own COMPLETE artifact
(<out minus .json>_tier0.json). A wedge anywhere in the full matrix can no
longer cost the round its three verdict cells. ``--tier0-only`` stops there.

WEDGE CONTAINMENT (round-4 verdict #6): every phase runs in a worker thread
with a wall-clock budget (_PhaseRunner). A phase that exceeds its budget is
recorded as skipped-by-budget and the capture moves on — one hung RPC cannot
consume the remaining window (the run-C SIGTERM precedent). After two
consecutive budget skips the tunnel is presumed wedged and later phases get
a short suspect budget, so they are still each ATTEMPTED (a transiently
recovered tunnel resumes normal budgets on the first success) while the
worst case stays bounded. A skipped phase that completes late is merged into
the artifact before the final write, flagged. Progress goes to <out>.partial
after every phase; the final artifact is renamed into place with a
completed_at marker.

Phase order within the full capture is most-valuable-first. The first
FRESH kernel compiles (the observed wedge trigger) happen deliberately
early — in tier-0 and phase 2c — because the kernel verdict cells are the
round's most valuable numbers and tier-0 banking plus per-phase budgets
bound the cost if one wedges.

All throughput cells use bench.py's two-point-slope protocol with forced
host readbacks: on the axon tunnel, dispatch is fully asynchronous and
jax.block_until_ready can return early, so naive loop timing measures
dispatch latency and reports physically impossible numbers (observed:
"334M samples/s" ~= 350 TFLOP/s fp32, above single-chip peak).

Writes TPU_CAPTURE_r<N>.json at the repo root and prints a summary table.
Run:  python scripts/tpu_capture.py [--quick] [--tier0-only]
A wedged tunnel is detected by bench.py's subprocess probe and aborts the
capture with exit 3 (nothing is written).
"""

import argparse
import json
import sys
import threading
import time
from pathlib import Path

import numpy as np

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

import bench  # the probe + the NumPy baseline + the headline protocol
from shallowspeed_tpu import retry

# Probe retries: this capture fronts bench._ensure_responsive_backend(),
# whose between-probe sleeps use the SAME shared bounded-backoff-with-
# jitter policy as scripts/tunnel_watch.sh and the checkpoint writer
# (shallowspeed_tpu.retry) — no fixed-cadence hammering anywhere in the
# tunnel tooling.


def _write_artifact(path, obj):
    """Artifact banking with the shared retry policy: one flaky host write
    must not cost the round its measured cells (the .partial after every
    phase IS the resume state; the renamed artifact IS the deliverable)."""
    retry.retry_call(
        lambda: Path(path).write_text(json.dumps(obj, indent=2) + "\n"),
        attempts=3,
        retry_on=(OSError,),
    )


def _measure_salvaged(run_ks, trials, samples_per_epoch):
    """The one measure-and-salvage policy for interleaved cell groups: run
    the same-window slope estimator with a failures dict (one unresolvable
    cell must not abort the capture), print + stringify the unresolved
    cells for the artifact, convert resolved slopes to samples/s. Returns
    ``(cells, unresolved)``; raise-on-empty is the CALLER's policy (the
    headline sweep needs a best cell; phase 5c can record an empty group)."""
    failures = {}
    slopes = bench.slope_epoch_seconds_many(run_ks, trials=trials, failures=failures)
    for name, err in failures.items():
        print(f"  UNRESOLVED {name}: {err}", flush=True)
    out = {}
    for name, slope in slopes.items():
        out[name] = round(samples_per_epoch / slope, 1)
        print(f"  {name}: {out[name]:,.0f} samples/s", flush=True)
    return out, {name: str(err) for name, err in failures.items()}


def _equality_record(outcome_a, outcome_b):
    """On-chip equality verdict from two ``(params_pytree, loss)`` outcomes
    of the same training step through two backends (ADVICE r03: measure the
    hardware divergence before timing instead of assuming the interpreter's
    bit-identity): per-leaf max-abs param diff, loss diff, bitwise flag."""
    import jax

    params_a, loss_a = outcome_a
    params_b, loss_b = outcome_b
    diffs = [
        float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
        for a, b in zip(jax.tree.leaves(params_a), jax.tree.leaves(params_b))
    ]
    return {
        "max_abs_param_diff": max(diffs),
        "loss_abs_diff": abs(loss_a - loss_b),
        "bitwise_equal": max(diffs) == 0.0 and loss_a == loss_b,
    }


def headline_sweep(unrolls, trials, precision="highest"):
    """Scan-unroll sweep of the fused sequential epoch, all unroll variants'
    trials interleaved (bench.slope_epoch_seconds_many) so the sweep is a
    same-window comparison rather than one cell per contention window."""
    import jax
    import jax.numpy as jnp

    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import trainer
    from shallowspeed_tpu.api import (
        FLAGSHIP_BATCH as B,
        FLAGSHIP_LR as LR,
        FLAGSHIP_MUBATCHES as M,
        FLAGSHIP_SIZES as SIZES,
        PRECISIONS,
    )
    from shallowspeed_tpu.optimizer import SGD

    spec = Mo.make_model_spec(SIZES, 1, B)
    nb = bench.N_SAMPLES // B
    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.rand(nb, M, B // M, SIZES[0]).astype(np.float32))
    Y = jnp.asarray(
        np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], (nb, M, B // M))]
    )
    run_ks = {}
    for unroll in unrolls:
        params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
        epoch = trainer.make_train_epoch(
            spec, SGD(LR), precision=PRECISIONS[precision],
            fuse_mubatches=True, unroll=unroll,
        )
        run_ks[f"unroll={unroll}"] = bench.make_run_k(epoch, params, (), X, Y)
    # unresolved cells go into the artifact too: a partial sweep must be
    # distinguishable from a complete one (best-of-sweep over different cell
    # sets is not comparable across captures)
    out, unresolved = _measure_salvaged(run_ks, trials, nb * B)
    if not out:
        raise RuntimeError(
            f"headline sweep ({precision}): every unroll cell unresolved: {unresolved}"
        )
    return out, unresolved


def _runkernel_wallclock_sps(run_fn, params, opt_state, X, Y, ref_sps, trials):
    """Whole-dispatch wall-clock for the run kernel (the
    bench.crosscheck_whole_run_sps pattern): the slope protocol would
    recompile for every adapted leg size (static n_epochs), polluting timed
    legs with Mosaic compiles — the documented wedge trigger. Instead, size
    K to ~2 s of expected device work from an already-resolved sibling
    cell's slope, pre-compile + warm with ONE fresh compile, then take the
    best-of-``trials`` plain wall of a single K-epoch dispatch ending in a
    forced readback (one RTT constant amortized to a few percent over ~2 s
    of work)."""
    samples_per_epoch = X.shape[0] * X.shape[1] * X.shape[2]
    K = int(min(1000, max(8, 2.0 * ref_sps / samples_per_epoch)))
    p, st, _ = run_fn(params, opt_state, X, Y, K)  # compile + warm
    bench.sync_readback(p)
    best = None
    for _ in range(trials):
        t0 = time.perf_counter()
        p, st, _ = run_fn(p, st, X, Y, K)
        bench.sync_readback(p)
        wall = time.perf_counter() - t0
        best = wall if best is None else min(best, wall)
    return samples_per_epoch * K / best


def _kernel_variant_cells(opt, precisions, key_fmt, nb, trials, label):
    """Shared measurement for one optimizer's kernel LADDER — fused xla vs
    mega (one op/batch) vs epoch (one op/epoch) vs run (one op for ALL the
    timed epochs): the on-chip equality probe runs FIRST (ADVICE r03 — the
    kernels' bit-identity with fused XLA is interpreter-verified on CPU,
    but Mosaic's compiled lowering is not guaranteed bitwise-equal on
    hardware, so the actual divergence of one 2-batch epoch from identical
    params+state is measured and recorded), then every (precision, variant)
    cell is timed with interleaved trials so all ratios are same-window.
    ONE definition for the SGD and adam phases so the probe/timing
    discipline cannot drift."""
    import jax
    import jax.numpy as jnp

    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import trainer
    from shallowspeed_tpu.api import (
        FLAGSHIP_BATCH as B,
        FLAGSHIP_MUBATCHES as M,
        FLAGSHIP_SIZES as SIZES,
        PRECISIONS,
    )

    spec = Mo.make_model_spec(SIZES, 1, B)
    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.rand(nb, M, B // M, SIZES[0]).astype(np.float32))
    Y = jnp.asarray(
        np.eye(sizes_last := SIZES[-1], dtype=np.float32)[
            rng.randint(0, sizes_last, (nb, M, B // M))
        ]
    )
    VARIANTS = {
        "xla": {},
        "mega": {"megakernel": True},
        "epoch": {"epoch_kernel": True},
        "run": None,  # equality-probed here; timed by _runkernel_wallclock_sps
    }

    def make_run_fn(prec):
        return trainer.make_train_run(
            spec, opt, precision=PRECISIONS[prec], fuse_mubatches=True,
            with_eval=False, run_kernel=True,
        )

    eq_outs = {}
    for name, kw in VARIANTS.items():
        params0 = jax.tree.map(jnp.asarray, Mo.init_model(spec))
        if name == "run":
            p, st, losses = make_run_fn("highest")(
                params0, opt.init(params0), X[:2], Y[:2], 1
            )
            loss = float(losses[0])
        else:
            epoch = trainer.make_train_epoch(
                spec, opt, precision=PRECISIONS["highest"], fuse_mubatches=True,
                **kw,
            )
            p, st, loss = epoch(params0, opt.init(params0), X[:2], Y[:2])
        # params AND optimizer state in the equality tree (state is () for
        # SGD, so the record is unchanged there)
        eq_outs[name] = ((jax.device_get(p), jax.device_get(st)), float(loss))
    equality = {
        name: _equality_record(eq_outs["xla"], eq_outs[name])
        for name in ("mega", "epoch", "run")
    }
    print(f"  on-chip {label} equality vs fused-xla (fp32): {equality}", flush=True)

    run_ks = {}
    for prec in precisions:
        for name, kw in VARIANTS.items():
            if name == "run":
                continue  # whole-dispatch wall-clock below, not slope legs
            params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
            key = key_fmt.format(prec=prec, name=name)
            epoch = trainer.make_train_epoch(
                spec, opt, precision=PRECISIONS[prec], fuse_mubatches=True,
                **kw,
            )
            run_ks[key] = bench.make_run_k(epoch, params, opt.init(params), X, Y)
            print(f"  built {key}", file=sys.stderr, flush=True)
    cells, unresolved = _measure_salvaged(run_ks, trials, nb * B)
    for prec in precisions:
        key = key_fmt.format(prec=prec, name="run")
        ref_sps = cells.get(key_fmt.format(prec=prec, name="epoch")) or cells.get(
            key_fmt.format(prec=prec, name="xla")
        )
        if not ref_sps:
            unresolved[key] = "no resolved sibling cell to size the dispatch from"
            continue
        try:
            params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
            sps = _runkernel_wallclock_sps(
                make_run_fn(prec), params, opt.init(params), X, Y, ref_sps,
                trials,
            )
        except Exception as e:  # noqa: BLE001 — one cell must not abort the set
            unresolved[key] = f"{type(e).__name__}: {e}"
            continue
        cells[key] = round(sps, 1)
        print(f"  {key}: {cells[key]:,.0f} samples/s (whole-dispatch wall)",
              flush=True)
    return cells, unresolved, equality


def megakernel_cells(nb, trials):
    """Same-window SGD triple at both precision classes: fused XLA epoch vs
    the whole-batch mega-kernel (one op per batch) vs the whole-EPOCH kernel
    (one op per epoch) — both via pallas_ops.fused_train_call. The roofline
    says the epoch is op-issue bound; these are the direct attacks at two
    strengths (see _kernel_variant_cells for the probe/timing discipline)."""
    from shallowspeed_tpu.api import FLAGSHIP_LR as LR
    from shallowspeed_tpu.optimizer import SGD

    return _kernel_variant_cells(
        SGD(LR), ("default", "highest"), "fused+{prec}+{name}", nb, trials,
        label="sgd-kernel",
    )


def megakernel_convergence(data_dir, epochs, variant="megakernel"):
    """20-epoch flagship convergence THROUGH the mega-kernel (or the
    whole-epoch kernel, ``variant='epoch_kernel'``) at the headline
    (default) precision — the evidence that lets the kernel carry the
    published headline: final accuracy must match the fused-XLA trajectory
    (TPU_DEFAULT_PRECISION_r02.json: 99.40%)."""
    from shallowspeed_tpu.api import TrainingSession

    run = TrainingSession(
        data_dir=data_dir, precision="default", fuse_mubatches=True,
        **{variant: True},
    )
    losses, accs = run.train_run(epochs)
    result = {
        "variant": variant,
        "precision": "default",
        "epochs": epochs,
        "per_epoch_val_accuracy": [round(float(a), 4) for a in accs],
        "final_val_accuracy": round(float(accs[-1]), 4),
        "first_loss": round(float(losses[0]), 4),
        "final_loss": round(float(losses[-1]), 4),
        "model_hash": run.model_hash(),
    }
    print(f"  megakernel convergence: {result}", flush=True)
    return result


def executor_backend_cells(nb, trials):
    """Pipeline-executor epoch on one chip (dp=pp=1 degenerate pipeline —
    the tick scan, stacked params and mailbox machinery run for real): XLA
    vs Pallas kernel backends (executor.make_pipeline_step(kernel_backend=))
    at both precision classes, interleaved so every ratio is same-window.
    The pallas cells compile the flag-operand kernels non-interpret."""
    import jax
    import jax.numpy as jnp

    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import schedules as S
    from shallowspeed_tpu.api import (
        FLAGSHIP_BATCH as B,
        FLAGSHIP_LR as LR,
        FLAGSHIP_MUBATCHES as M,
        FLAGSHIP_SIZES as SIZES,
        PRECISIONS,
    )
    from shallowspeed_tpu.optimizer import SGD
    from shallowspeed_tpu.parallel import executor as E, lower_schedule, make_mesh

    mesh = make_mesh(1, 1)
    spec = Mo.make_model_spec(SIZES, 1, B)
    prog = lower_schedule(S.GPipeSchedule, M, 1)
    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.rand(nb, B, SIZES[0]).astype(np.float32))
    Y = jnp.asarray(
        np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], (nb, B))]
    )
    # On-chip equality probe BEFORE timing (ADVICE r03): one pipeline step
    # through each backend from identical stacked params — the flag kernels'
    # bit-identity is interpreter-verified on CPU; on hardware Mosaic's
    # lowering may differ from XLA's, so record the observed divergence.
    eq_outs = {}
    for kb in ("xla", "pallas"):
        step = E.make_pipeline_step(
            mesh, spec, prog, B // M, SGD(LR),
            precision=PRECISIONS["highest"], kernel_backend=kb,
        )
        stacked0, flags0 = E.init_stacked(spec, mesh)
        new_stacked, _, loss = step(stacked0, flags0, (), X[0], Y[0])
        eq_outs[kb] = (jax.device_get(new_stacked), float(loss))
    equality = _equality_record(eq_outs["xla"], eq_outs["pallas"])
    print(f"  on-chip equality (pallas vs xla executor, fp32): {equality}", flush=True)

    run_ks = {}
    for prec in ("default", "highest"):
        for kb in ("xla", "pallas"):
            epoch = E.make_pipeline_epoch(
                mesh, spec, prog, B // M, SGD(LR),
                precision=PRECISIONS[prec], kernel_backend=kb,
            )
            stacked, flags = E.init_stacked(spec, mesh)

            def fn(p, s, X, Y, _epoch=epoch, _flags=flags):
                return _epoch(p, _flags, s, X, Y)

            key = f"executor+{prec}+{kb}"
            run_ks[key] = bench.make_run_k(fn, stacked, (), X, Y)
            print(f"  built {key}", file=sys.stderr, flush=True)
    cells, unresolved = _measure_salvaged(run_ks, trials, nb * B)
    return cells, unresolved, equality


def executor_backend_api_path(data_dir, epochs=2):
    """The executor's Pallas backend through the PRODUCT surface on the chip:
    two TrainingSessions (interleaved V=2 on one device — the API's route to
    the tick executor on a single chip), kernel_backend xla vs pallas, same
    seeds; train ``epochs`` epochs and compare loss trajectories + final
    model hashes. This is the capture-side witness that the user-facing
    ``kernel_backend`` flag runs the same training the direct executor
    cells measure."""
    from shallowspeed_tpu.api import TrainingSession

    out = {}
    for kb in ("xla", "pallas"):
        run = TrainingSession(
            data_dir=data_dir, pp=1, schedule="interleaved", virtual_stages=2,
            kernel_backend=kb,
        )
        losses = [round(run.train_epoch(), 6) for _ in range(epochs)]
        out[kb] = {"losses": losses, "model_hash": run.model_hash()}
    out["hashes_match"] = out["xla"]["model_hash"] == out["pallas"]["model_hash"]
    out["losses_match"] = out["xla"]["losses"] == out["pallas"]["losses"]
    print(f"  API-path executor backends: {out}", flush=True)
    return out


def adam_kernel_cells(nb, trials):
    """Same-window adam triple at the headline precision — adam's few-epoch
    sweet spot (99.86% after ONE epoch in the round-2 soak) is exactly what
    a one-op epoch serves (see _kernel_variant_cells)."""
    from shallowspeed_tpu.optimizer import Adam

    return _kernel_variant_cells(
        Adam(2e-4), ("default",), "adam+{prec}+{name}", nb, trials,
        label="adam-kernel",
    )


def adam_epoch_kernel_convergence(data_dir):
    """1-epoch adam convergence through the epoch kernel at the HEADLINE
    (default) precision — the config the adam cells time and the README
    claim cites."""
    from shallowspeed_tpu.api import TrainingSession

    run = TrainingSession(
        data_dir=data_dir, optimizer="adam", lr=2e-4, precision="default",
        fuse_mubatches=True, epoch_kernel=True,
    )
    losses, accs = run.train_run(1)
    result = {
        "precision": "default",
        "loss": round(losses[0], 4),
        "val_accuracy": round(accs[0], 4),
        "model_hash": run.model_hash(),
    }
    print(f"  adam 1-epoch: {result}", flush=True)
    return result


def convergence_run(data_dir, epochs):
    from shallowspeed_tpu.api import TrainingSession

    run = TrainingSession(data_dir=data_dir)
    # settle the one-time host->device dataset upload before the clock starts
    # (async dispatch would otherwise bill it to epoch 1)
    import numpy as _np

    for attr in ("_X", "_Y", "_Xe", "_Ye"):
        arr = getattr(run, attr, None)
        if arr is not None:
            _np.asarray(arr[(0,) * (arr.ndim - 1) + (slice(0, 1),)])
    accs, losses = [], []
    train_time = 0.0
    for _ in range(epochs):
        t0 = time.perf_counter()
        losses.append(run.train_epoch())
        train_time += time.perf_counter() - t0  # eval excluded from the clock
        accs.append(round(run.accuracy(), 4))
    n = run.batches_per_epoch * run.B * epochs
    result = {
        "epochs": epochs,
        "train_wall_s": round(train_time, 3),
        "train_samples_per_sec": round(n / train_time, 1),
        "per_epoch_val_accuracy": accs,
        "final_val_accuracy": accs[-1],
        "first_loss": round(losses[0], 4),
        "final_loss": round(losses[-1], 4),
        "model_hash": run.model_hash(),
    }
    print(f"  convergence: {result}", flush=True)

    # fused-run variant: the same epochs + per-epoch accuracy as ONE
    # on-device program (api.train_run) — no per-epoch readback RTTs.
    # The first call pays the compile; a second call on the SAME session
    # (the jit cache is per run-function object) reuses the executable and
    # gives the steady-state wall for `epochs` more epochs of identical
    # shape/work (the training state having advanced doesn't change the
    # per-epoch cost).
    fused = TrainingSession(data_dir=data_dir)
    t0 = time.perf_counter()
    losses_f, accs_f = fused.train_run(epochs)
    compile_and_run_s = time.perf_counter() - t0
    from_scratch_hash = fused.model_hash()
    t0 = time.perf_counter()
    fused.train_run(epochs)
    fused_wall = time.perf_counter() - t0
    result["fused_run"] = {
        "steady_state_wall_s": round(fused_wall, 3),
        "compile_and_run_wall_s": round(compile_and_run_s, 3),
        "samples_per_sec_incl_eval": round(n / fused_wall, 1),
        "final_val_accuracy_first_run": round(accs_f[-1], 4),
        "final_loss_first_run": round(losses_f[-1], 4),
        "matches_epoch_loop_hash": from_scratch_hash == result["model_hash"],
    }
    print(f"  fused-run: {result['fused_run']}", flush=True)
    return result


def profile_one_epoch(data_dir, trace_dir):
    import jax

    from shallowspeed_tpu.api import TrainingSession

    run = TrainingSession(data_dir=data_dir)
    run.train_epoch()  # compile
    with jax.profiler.trace(str(trace_dir)):
        run.train_epoch()
    files = [str(p.relative_to(trace_dir)) for p in Path(trace_dir).rglob("*") if p.is_file()]
    print(f"  trace: {len(files)} files in {trace_dir}", flush=True)
    return {"dir": str(trace_dir), "n_files": len(files)}


def profile_headline_epoch(trace_dir):
    """Trace one post-compile epoch of the HEADLINE config (fused +
    default precision — what `python bench.py` publishes), feeding the
    roofline analysis in docs/performance.md with per-op numbers for the
    exact program being scored."""
    import jax

    epoch, params, X, Y = bench._jax_epoch_setup("default")
    params, st, _ = epoch(params, (), X, Y)  # compile + warm
    bench.sync_readback(params)
    with jax.profiler.trace(str(trace_dir)):
        params, st, _ = epoch(params, st, X, Y)
        bench.sync_readback(params)
    files = [str(p.relative_to(trace_dir)) for p in Path(trace_dir).rglob("*") if p.is_file()]
    print(f"  headline trace: {len(files)} files in {trace_dir}", flush=True)
    return {"dir": str(trace_dir), "n_files": len(files)}


# Per-phase wall-clock budgets (seconds). Generous for healthy runs — their
# job is to stop ONE wedged RPC from consuming the remaining claim window,
# not to tightly bound healthy phases. Monkeypatchable by the plumbing test.
PHASE_BUDGET_S = {
    "t0-baseline": 300, "t0-headline-pair": 1200, "t0-kernel-cells": 1800,
    "t0-vmem": 900,
    "1-baseline": 300,
    "2-headline-default": 1500, "2b-headline-fp32": 1200,
    "2c-kernel-cells": 1800,
    "3-convergence": 1500, "3b-mega-convergence": 1200,
    "3c-epoch-convergence": 1200,
    "4-trace": 600, "4b-trace-headline": 600,
    "5-matrix": 1800, "5b-matrix-full": 1800, "5c-executor-backends": 1200,
    "5d-executor-api": 900, "6-adam-cells": 1500, "6b-adam-convergence": 600,
}
# phase -> primary result key: a phase whose key is already present in a
# --resume'd artifact is not re-run; also the reverse index resume uses to
# INVALIDATE keys measured by late-completed / contamination-flagged phases
# (their one chance at a clean re-measure is exactly the resumed window)
PHASE_DONE_KEYS = {
    "t0-baseline": "numpy_baseline_sps",
    "t0-headline-pair": "headline_pair",
    "t0-kernel-cells": "kernel_cells_default",
    "t0-vmem": "epoch_kernel_vmem",
    "1-baseline": "numpy_baseline_sps",
    "2-headline-default": "headline_sweep_default_precision",
    "2b-headline-fp32": "headline_sweep_fp32_highest",
    "2c-kernel-cells": "megakernel_cells",
    "3-convergence": "convergence",
    "3b-mega-convergence": "megakernel_convergence",
    "3c-epoch-convergence": "epoch_kernel_convergence",
    "4-trace": "trace",
    "4b-trace-headline": "trace_headline",
    "5-matrix": "matrix",
    "5b-matrix-full": "matrix_full_epoch_fused",
    "5c-executor-backends": "executor_kernel_backends",
    "5d-executor-api": "executor_api_path",
    "6-adam-cells": "adam_kernel_cells",
    "6b-adam-convergence": "adam_epoch_kernel_one_epoch",
}

# phase -> the key its cell fn records when SOME cells failed to resolve
# (ADVICE r05): a resumed run must re-attempt such phases — their primary
# key being present only means the phase ran, not that it delivered — so
# done-detection requires the primary key non-empty AND no unresolved key.
PHASE_UNRESOLVED_KEYS = {
    "t0-kernel-cells": "kernel_cells_unresolved",
    "2-headline-default": "headline_sweep_default_unresolved",
    "2b-headline-fp32": "headline_sweep_fp32_unresolved",
    "2c-kernel-cells": "megakernel_cells_unresolved",
    "5c-executor-backends": "executor_kernel_backends_unresolved",
    "6-adam-cells": "adam_kernel_cells_unresolved",
}

def capture_complete(result):
    """Rename-into-place eligibility for the FULL capture (ADVICE r05):
    nothing budget-skipped AND no ``*_unresolved`` cell markers — both are
    transient failure classes a ``--resume`` retry can fix (the resume
    done-detection treats unresolved phases as undelivered, so the gate
    must agree or tunnel_watch.sh would exit on an artifact resume still
    wants to improve). Deterministic ``phase_errors`` do NOT gate:
    re-running them fails identically, and a banked artifact with recorded
    errors beats an endless watch loop."""
    if result.get("phases_skipped_by_budget"):
        return False
    return not any(k in result for k in PHASE_UNRESOLVED_KEYS.values())


# after two consecutive budget skips the tunnel is presumed wedged: later
# phases still run (each must be ATTEMPTED per the round-4 verdict) but at
# this short budget, so the worst case stays bounded well under the watcher
# window; the first success restores normal budgets
SUSPECT_BUDGET_S = 300


class _PhaseRunner:
    """Budget-bounded phase execution (round-4 verdict #6).

    Each phase is a zero-arg closure returning a dict of result updates; it
    runs in a daemon worker thread and the main thread waits at most the
    phase's budget. On timeout the phase is recorded under
    ``phases_skipped_by_budget`` and the capture moves on — the hung thread
    is abandoned (a wedged tunnel RPC cannot be interrupted from Python). If
    an abandoned phase completes while later phases run, ``merge_late``
    folds its updates into the artifact before the final write (without
    overwriting keys a later phase produced) and flags it. Exceptions are
    recorded under ``phase_errors`` and do NOT abort the capture: a fast
    failure answered, so it resets the consecutive-skip wedge counter."""

    def __init__(self, result, checkpoint):
        self.result = result
        self.checkpoint = checkpoint
        self.consecutive_skips = 0
        self._late = []  # (label, box) of abandoned phases

    def run(self, label, fn):
        # resume support: a phase whose primary result key is already in
        # ``result`` (loaded from a previous run's .partial) is not re-run —
        # a killed chip window must not cost re-measuring completed phases.
        # "Done" requires the key to be NON-EMPTY and no matching
        # ``*_unresolved`` key (ADVICE r05): a phase that recorded an empty
        # cell dict, or banked only SOME of its cells before a wedge, has
        # not delivered — the resumed (healthy) window is its chance to.
        done_key = PHASE_DONE_KEYS.get(label)
        unres_key = PHASE_UNRESOLVED_KEYS.get(label)
        if (
            done_key is not None
            and self.result.get(done_key)
            and (unres_key is None or unres_key not in self.result)
        ):
            print(f"  PHASE {label}: already captured ({done_key}); skipping",
                  flush=True)
            return True
        budget = PHASE_BUDGET_S.get(label, 900)
        if self.consecutive_skips >= 2:
            budget = min(budget, SUSPECT_BUDGET_S)
        box = {}

        def work():
            try:
                box["updates"] = fn()
            except Exception as e:  # noqa: BLE001 — recorded, not fatal
                box["error"] = f"{type(e).__name__}: {e}"

        # contamination honesty: an abandoned over-budget thread keeps
        # issuing device work in this process; any phase that starts while
        # one is still unfinished may share the chip with it, so its cells
        # must carry a flag rather than read as clean
        concurrent = [lbl for lbl, b in self._late if not b]
        if concurrent:
            self.result.setdefault(
                "phases_with_concurrent_abandoned_work", {}
            )[label] = concurrent
        t = threading.Thread(target=work, daemon=True, name=f"phase-{label}")
        t_start = time.monotonic()
        t.start()
        t.join(budget)
        took = round(time.monotonic() - t_start, 1)
        if t.is_alive():
            self.consecutive_skips += 1
            self.result.setdefault("phases_skipped_by_budget", []).append(
                {"phase": label, "budget_s": budget}
            )
            self._late.append((label, box))
            print(
                f"  PHASE {label} exceeded its {budget}s budget; "
                "skipping forward (wedge containment)",
                flush=True,
            )
            self.checkpoint()
            return False
        if "error" in box:
            self.consecutive_skips = 0
            self.result.setdefault("phase_errors", []).append(
                {"phase": label, "error": box["error"]}
            )
            print(f"  PHASE {label} failed: {box['error']}", flush=True)
            self.checkpoint()
            return False
        self.consecutive_skips = 0
        updates = box.get("updates") or {}
        if unres_key is not None and unres_key not in updates:
            # a clean re-run supersedes a prior run's partial cells: drop
            # the stale unresolved marker so the phase reads as delivered
            self.result.pop(unres_key, None)
        self.result.update(updates)
        self.result.setdefault("phase_seconds", {})[label] = took
        self.checkpoint()
        return True

    def merge_late(self):
        for label, box in self._late:
            if "updates" in box:
                for k, v in (box["updates"] or {}).items():
                    self.result.setdefault(k, v)
                self.result.setdefault("phases_late_completed", []).append(label)


def tier0_phases(runner, quick):
    """The three verdict cells (round-4 verdict #1), cheapest-complete form:
    NumPy denominator, the fused default/highest headline pair at the
    default unroll (bench.jax_sps_many — interleaved, same-window), and the
    sgd xla/mega/epoch kernel triple at the headline precision with its
    fp32 on-chip equality probes (probes run first inside the cell fn)."""

    def t0_baseline():
        b = bench.numpy_baseline_sps(n_batches=10)
        print(f"  numpy: {b:,.0f} samples/s", flush=True)
        return {"numpy_baseline_sps": round(b, 1)}

    runner.run("t0-baseline", t0_baseline)

    def t0_pair():
        pair = bench.jax_sps_many(("default", "highest"), trials=2)
        upd = {"headline_pair": {k: round(v, 1) for k, v in pair.items()}}
        base = runner.result.get("numpy_baseline_sps")
        if "default" in pair:
            upd["headline_best_sps"] = round(pair["default"], 1)
            if base:
                upd["vs_baseline"] = round(pair["default"] / base, 2)
        for k, v in upd["headline_pair"].items():
            print(f"  {k}: {v:,.0f} samples/s", flush=True)
        return upd

    runner.run("t0-headline-pair", t0_pair)

    def t0_kernels():
        from shallowspeed_tpu.api import FLAGSHIP_LR as LR
        from shallowspeed_tpu.optimizer import SGD

        cells, unresolved, eq = _kernel_variant_cells(
            SGD(LR), ("default",), "fused+{prec}+{name}",
            14 if quick else 29, 2, label="sgd-kernel",
        )
        upd = {"kernel_cells_default": cells, "kernel_onchip_equality": eq}
        if unresolved:
            upd["kernel_cells_unresolved"] = unresolved
        return upd

    runner.run("t0-kernel-cells", t0_kernels)


def epoch_kernel_vmem_analysis(sizes=None, B=None, M=None):
    """Compile-time calibration of the ADVISORY VMEM fits-predicate
    (round-4 verdict #5): lower + compile the whole-epoch kernel — sgd,
    and adam (two state mirrors, the largest footprint) — WITHOUT running
    it, and record the compiler's own memory analysis next to the
    predicate's byte model. Mosaic does not expose per-kernel VMEM
    directly, but a successful compile at these shapes is exactly the
    signal the predicate guesses at (a VMEM overflow fails the compile),
    and the analysis numbers bound the byte model. Defaults to the
    flagship config; the shape parameters exist so the test suite can run
    the REAL body fast (a capture phase must never be test-covered only
    by a stub — its signature breaking would burn the chip window)."""
    import jax
    import jax.numpy as jnp

    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import pallas_ops, trainer
    from shallowspeed_tpu import api
    from shallowspeed_tpu.api import FLAGSHIP_LR as LR, PRECISIONS
    from shallowspeed_tpu.optimizer import SGD, Adam

    sizes = tuple(sizes) if sizes else api.FLAGSHIP_SIZES
    B = B or api.FLAGSHIP_BATCH
    M = M or api.FLAGSHIP_MUBATCHES
    SIZES = sizes
    spec = Mo.make_model_spec(SIZES, 1, B)
    rng = np.random.RandomState(0)
    nb = 4  # grid length; per-step VMEM depends on batch rows, not nb
    X = jnp.asarray(rng.rand(nb, M, B // M, SIZES[0]).astype(np.float32))
    Y = jnp.asarray(
        np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], (nb, M, B // M))]
    )
    out = {}
    for name, opt, mirrors in (("sgd", SGD(LR), 0), ("adam", Adam(2e-4), 2)):
        epoch = trainer.make_train_epoch(
            spec, opt, precision=PRECISIONS["default"], fuse_mubatches=True,
            epoch_kernel=True,
        )
        params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
        compiled = epoch.lower(params, opt.init(params), X, Y).compile()
        # the ONE shared memory_analysis path (observability/program_audit):
        # same field set as before plus the peak_hbm_bytes estimate, and the
        # same helper TrainingSession audits and bench.py records use — the
        # three byte accountings can never disagree
        from shallowspeed_tpu.observability.program_audit import memory_stats

        rec = {"compiled_ok": True}
        rec.update(memory_stats(compiled) or {})
        rec["predicted_kernel_bytes"] = pallas_ops._kernel_bytes(
            B, SIZES, state_mirrors=mirrors
        )
        rec["fits_predicate"] = pallas_ops.train_epoch_kernel_fits(
            B, SIZES, state_mirrors=mirrors
        )
        out[name] = rec
        print(f"  epoch-kernel compile [{name}]: {rec}", flush=True)
    out["budget_bytes"] = pallas_ops.SINGLE_BLOCK_BUDGET_BYTES
    return {"epoch_kernel_vmem": out}


def _load_resume_state(result, paths, config_sig):
    """Fold a previous run's artifact into ``result`` for --resume: captured
    keys make their phases skip (PHASE_DONE_KEYS match); the PRIOR run's
    skip/error/flag bookkeeping (and its info block) is moved aside under
    ``prior_run`` so retried phases get fresh flags this run.

    Honesty rules:
    - a truncated/corrupt artifact (the prior run was killed mid-
      checkpoint — exactly the scenario resume exists for) is skipped with
      a note, never a crash; the next path is tried;
    - an artifact captured under a DIFFERENT config (quick/data-dir) is
      ignored entirely — quick-config cells must not silently merge into a
      full-config artifact — and the mismatch is recorded;
    - keys measured by late-completed or contamination-flagged phases are
      DROPPED so those phases re-run: the resumed (healthy) window is
      their one chance at a clean re-measure."""
    for path in paths:
        if not Path(path).is_file():
            continue
        try:
            prev = json.loads(Path(path).read_text())
        except ValueError as e:
            print(f"  resume: {path} is not valid JSON ({e}); skipping it",
                  flush=True)
            result.setdefault("resume_unreadable_artifacts", []).append(str(path))
            continue
        if prev.get("capture_config") != config_sig:
            print(
                f"  resume: {path} was captured under a different config "
                f"({prev.get('capture_config')!r} != {config_sig!r}); "
                "ignoring it", flush=True,
            )
            result.setdefault("resume_ignored_mismatched", []).append(
                {"path": str(path), "capture_config": prev.get("capture_config")}
            )
            continue
        suspect_phases = list(prev.get("phases_late_completed", [])) + list(
            prev.get("phases_with_concurrent_abandoned_work", {})
        )
        for ph in suspect_phases:
            key = PHASE_DONE_KEYS.get(ph)
            if key and key in prev:
                print(
                    f"  resume: dropping {key!r} (phase {ph} was "
                    "late/contaminated in the prior run; re-measuring)",
                    flush=True,
                )
                prev.pop(key)
        prior = {}
        for k in (
            "phases_skipped_by_budget", "phase_errors",
            "phases_late_completed", "phases_with_concurrent_abandoned_work",
            "completed_at", "info", "phase_seconds",
        ):
            if k in prev:
                prior[k] = prev.pop(k)
        for k, v in prev.items():
            result.setdefault(k, v)
        if prior:
            result.setdefault("prior_run", {}).update(prior)
        print(f"  resume: loaded {path}", flush=True)
        return  # first existing file wins (complete beats partial)


def _finalize_ratios(result):
    """Fill derived ratio keys from whichever phases delivered their
    operands — under --resume the baseline and a sweep can come from
    DIFFERENT runs, so the ratios cannot live only inside the sweep
    phases. Never overwrites an already-computed value."""
    base = result.get("numpy_baseline_sps")
    if not base:
        return
    pair = result.get("headline_pair") or {}
    if "vs_baseline" not in result:
        best = result.get("headline_best_sps") or pair.get("default")
        if best:
            result["vs_baseline"] = round(best / base, 2)
    if "vs_baseline_fp32" not in result:
        best32 = result.get("headline_best_fp32_sps")
        if best32:
            result["vs_baseline_fp32"] = round(best32 / base, 2)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default="/tmp/ssd_data")
    ap.add_argument("--quick", action="store_true", help="fewer reps/epochs")
    ap.add_argument("--tier0-only", action="store_true",
                    help="bank the tier-0 artifact and stop")
    ap.add_argument("--resume", action="store_true",
                    help="load a previous run's artifacts (tier-0 file and "
                    "<out>.partial) and skip phases already captured")
    ap.add_argument("--out", default=str(ROOT / "TPU_CAPTURE_r05.json"))
    args = ap.parse_args()

    tag, _probe_diag = bench._ensure_responsive_backend()
    if tag:
        print(f"tunnel not healthy ({tag}); aborting capture", file=sys.stderr)
        sys.exit(3)

    import jax

    dev = jax.devices()[0]
    info = {
        "platform": dev.platform,
        "device": str(dev),
        "captured_at": time.strftime("%Y-%m-%d %H:%M:%S"),
    }
    print(f"device: {info['device']} ({info['platform']})", flush=True)

    if not Path(args.data_dir).is_dir():
        import subprocess

        subprocess.run(
            [sys.executable, str(ROOT / "prepare_data.py"), "--save-dir", args.data_dir],
            check=True,
        )

    # ---- TIER 0: bank the verdict cells as a complete artifact FIRST ----
    t0_out = Path(args.out).with_name(Path(args.out).stem + "_tier0.json")
    t0_partial = Path(str(t0_out) + ".partial")
    config_sig = {"quick": bool(args.quick), "data_dir": str(args.data_dir)}
    t0_result = {"info": dict(info), "tier": 0, "capture_config": config_sig}
    if args.resume:
        _load_resume_state(t0_result, (t0_out, t0_partial), config_sig)
    runner0 = _PhaseRunner(
        t0_result,
        lambda: _write_artifact(t0_partial, t0_result),
    )
    print("tier-0: headline pair + kernel triple + equality probes...", flush=True)
    tier0_phases(runner0, args.quick)
    runner0.merge_late()
    _finalize_ratios(t0_result)
    # the rename-into-place marker means "verdict cells banked": only stamp
    # completed_at and promote the file when every tier-0 phase actually
    # delivered — a skipped/errored tier-0 stays a .partial, unmistakably
    t0_complete = not t0_result.get("phases_skipped_by_budget") and not (
        t0_result.get("phase_errors")
    )
    if t0_complete:
        t0_result["completed_at"] = time.strftime("%Y-%m-%d %H:%M:%S")
    _write_artifact(t0_partial, t0_result)
    if t0_complete:
        t0_partial.rename(t0_out)
        print(f"tier-0 artifact banked: {t0_out}", flush=True)
    else:
        print(f"tier-0 INCOMPLETE — kept as {t0_partial}", flush=True)
    # VMEM calibration runs AFTER banking so a compile failure/timeout —
    # the exact case it exists to probe — can never un-bank the measured
    # verdict cells; its outcome (or error) is appended as diagnostics.
    # The runner's checkpoint is redirected to the banked file first, so
    # the phase cannot resurrect a stale .partial next to it.
    banked_path = t0_out if t0_complete else t0_partial
    runner0.checkpoint = lambda: _write_artifact(banked_path, t0_result)
    print("t0b) epoch-kernel VMEM calibration compile...", flush=True)
    runner0.run("t0-vmem", epoch_kernel_vmem_analysis)
    _write_artifact(banked_path, t0_result)
    if args.tier0_only:
        print(json.dumps({
            "tier0": str(t0_out),
            "headline_best_sps": t0_result.get("headline_best_sps"),
            "vs_baseline": t0_result.get("vs_baseline"),
        }))
        return

    # ---- full capture: most-valuable-first, per-phase budgets ----
    result = {"info": info, "capture_config": config_sig}
    partial_path = Path(str(args.out) + ".partial")
    if args.resume:
        # pass BOTH the banked artifact and the .partial (mirroring the
        # tier-0 call; complete beats partial): once a capture has been
        # renamed into <out>, a later --resume must build on it instead of
        # re-measuring every phase and overwriting it (ADVICE r05)
        _load_resume_state(result, (Path(args.out), partial_path), config_sig)
    runner = _PhaseRunner(
        result,
        lambda: _write_artifact(partial_path, result),
    )
    trials = 2 if args.quick else 3
    nb_cells = 29 if args.quick else 116

    print("1) NumPy baseline (host CPU)...", flush=True)

    def p1():
        baseline = bench.numpy_baseline_sps(n_batches=10 if args.quick else 40)
        print(f"  numpy: {baseline:,.0f} samples/s", flush=True)
        return {"numpy_baseline_sps": round(baseline, 1)}

    runner.run("1-baseline", p1)

    print("2) headline sweep (fused sequential epoch, DEFAULT precision "
          "— the convergence-verified bench headline config)...", flush=True)

    def p2():
        sweep, unresolved = headline_sweep((1, 2, 4, 8), trials, precision="default")
        best = max(sweep.values())
        upd = {"headline_sweep_default_precision": sweep, "headline_best_sps": best}
        if unresolved:
            upd["headline_sweep_default_unresolved"] = unresolved
        base = result.get("numpy_baseline_sps")
        if base:
            upd["vs_baseline"] = round(best / base, 2)
        return upd

    runner.run("2-headline-default", p2)

    print("2b) fp32 HIGHEST sweep (the bitwise-NumPy-parity config)...",
          flush=True)

    def p2b():
        sweep, unresolved = headline_sweep((1, 2, 4, 8), trials, precision="highest")
        best = max(sweep.values())
        upd = {"headline_sweep_fp32_highest": sweep, "headline_best_fp32_sps": best}
        if unresolved:
            upd["headline_sweep_fp32_unresolved"] = unresolved
        base = result.get("numpy_baseline_sps")
        if base:
            upd["vs_baseline_fp32"] = round(best / base, 2)
        return upd

    runner.run("2b-headline-fp32", p2b)

    print("2c) fused-XLA vs mega-kernel vs epoch-kernel (same-window, both "
          "precision classes; the op-issue-roofline attacks)...", flush=True)

    def p2c():
        mega, unresolved, eq = megakernel_cells(nb_cells, trials)
        upd = {"megakernel_cells": mega, "megakernel_onchip_equality": eq}
        if unresolved:
            upd["megakernel_cells_unresolved"] = unresolved
        return upd

    runner.run("2c-kernel-cells", p2c)

    print("3) convergence (real dataset, per-epoch eval)...", flush=True)
    runner.run("3-convergence", lambda: {
        "convergence": convergence_run(args.data_dir, 5 if args.quick else 20)
    })

    print("3b) mega-kernel convergence (headline precision)...", flush=True)
    runner.run("3b-mega-convergence", lambda: {
        "megakernel_convergence": megakernel_convergence(
            args.data_dir, 5 if args.quick else 20
        )
    })

    print("3c) epoch-kernel convergence (headline precision)...", flush=True)
    runner.run("3c-epoch-convergence", lambda: {
        "epoch_kernel_convergence": megakernel_convergence(
            args.data_dir, 5 if args.quick else 20, variant="epoch_kernel"
        )
    })

    # per-round trace dirs: the committed round-2 trace in artifacts/tpu_trace
    # is a pinned test fixture (test_trace_stats_reproduces_roofline_numbers)
    # and must never be appended to by a later capture
    print("4) profiler trace...", flush=True)
    runner.run("4-trace", lambda: {
        "trace": profile_one_epoch(args.data_dir, ROOT / "artifacts" / "tpu_trace_r05")
    })
    print("4b) headline-config (fused+default) trace...", flush=True)
    runner.run("4b-trace-headline", lambda: {
        "trace_headline": profile_headline_epoch(
            ROOT / "artifacts" / "tpu_trace_headline_r05"
        )
    })

    print("5) tuning matrix (interleaved cells, same-window ratios)...",
          flush=True)
    sys.path.insert(0, str(ROOT / "scripts"))
    from bench_tpu_matrix import ALL_CELLS, run_matrix

    def p5():
        raw = run_matrix(ALL_CELLS, nb_cells, 2)
        matrix = {}
        for key, sps in raw.items():
            matrix["+".join(key)] = round(sps, 1)
            print(f"  {'+'.join(key)}: {sps:,.0f} samples/s", flush=True)
        return {"matrix": matrix}

    runner.run("5-matrix", p5)

    print("5b) full-epoch fused cells: pallas vs xla at equal precision "
          "class (the kernels take the caller's precision)...", flush=True)

    def p5b():
        fused_cells = [(True, p, k) for p in ("highest", "default") for k in (False, True)]
        raw = run_matrix(fused_cells, 29 if args.quick else bench.N_SAMPLES // 128, 2)
        matrix = {}
        for key, sps in raw.items():
            matrix["+".join(key)] = round(sps, 1)
            print(f"  {'+'.join(key)}: {sps:,.0f} samples/s", flush=True)
        return {"matrix_full_epoch_fused": matrix}

    runner.run("5b-matrix-full", p5b)

    print("5c) pipeline-executor kernel backends (xla vs pallas flag "
          "kernels, dp=pp=1, same-window)...", flush=True)

    def p5c():
        cells, unresolved, eq = executor_backend_cells(nb_cells, 2)
        upd = {"executor_kernel_backends": cells, "executor_onchip_equality": eq}
        if unresolved:
            upd["executor_kernel_backends_unresolved"] = unresolved
        return upd

    runner.run("5c-executor-backends", p5c)

    print("5d) executor backend through the API surface "
          "(TrainingSession(kernel_backend=))...", flush=True)
    runner.run("5d-executor-api", lambda: {
        "executor_api_path": executor_backend_api_path(
            args.data_dir, epochs=1 if args.quick else 2
        )
    })

    print("6) adam kernel triple + 1-epoch adam convergence through the "
          "epoch kernel...", flush=True)

    def p6():
        cells, unresolved, eq = adam_kernel_cells(nb_cells, 2)
        upd = {"adam_kernel_cells": cells, "adam_onchip_equality": eq}
        if unresolved:
            upd["adam_kernel_cells_unresolved"] = unresolved
        return upd

    runner.run("6-adam-cells", p6)
    runner.run("6b-adam-convergence", lambda: {
        "adam_epoch_kernel_one_epoch": adam_epoch_kernel_convergence(
            args.data_dir
        )
    })

    runner.merge_late()
    _finalize_ratios(result)
    # rename-into-place gate, matching the tier-0 gate (ADVICE r05): a
    # wedged/partially-delivered capture stays a .partial so
    # tunnel_watch.sh keeps watching and retries it with --resume instead
    # of exiting on an incomplete artifact (see capture_complete for the
    # exact eligibility rules).
    complete = capture_complete(result)
    if complete:
        result["completed_at"] = time.strftime("%Y-%m-%d %H:%M:%S")
    _write_artifact(partial_path, result)
    if complete:
        partial_path.rename(args.out)
    else:
        print(
            f"capture INCOMPLETE (budget-skipped phases or unresolved "
            f"cells) — kept as {partial_path}; re-run with --resume",
            flush=True,
        )
    print(json.dumps({
        "headline_best_sps": result.get("headline_best_sps"),
        "vs_baseline": result.get("vs_baseline"),
        "tier0": str(t0_out),
        "phases_skipped_by_budget": [
            e["phase"] for e in result.get("phases_skipped_by_budget", [])
        ],
    }))


if __name__ == "__main__":
    main()
