"""Checkpoint tests: round-trip fidelity, cross-layout resume, and the
format-v2 fault-tolerance surface.

The design property under test: a checkpoint stores logical per-layer blocks
in global layer order, so save-from-one-layout / resume-into-another is exact
(the reference framework has no checkpointing at all, SURVEY §5.4). Format
v2 (docs/robustness.md) adds the step cursor, the content checksum that
detects torn/corrupted files, rotating step-snapshot retention, and
newest-first crash-recovery discovery that falls back past corrupt files.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shallowspeed_tpu import checkpoint as C
from shallowspeed_tpu import faults
from shallowspeed_tpu import model as Mo
from shallowspeed_tpu import schedules as S
from shallowspeed_tpu import trainer
from shallowspeed_tpu.checkpoint import (
    CheckpointError,
    find_latest_good,
    list_step_checkpoints,
    load_checkpoint,
    rotate_step_checkpoints,
    save_checkpoint,
    step_checkpoint_path,
    verify_checkpoint,
)
from shallowspeed_tpu.optimizer import SGD
from shallowspeed_tpu.parallel import executor as E
from shallowspeed_tpu.parallel import lower_schedule, make_mesh

SIZES = (24, 20, 18, 16, 14, 12, 11, 10)
B, M = 32, 4


def _train_sequential(params, spec, n=2, seed=0):
    rng = np.random.RandomState(seed)
    step = trainer.make_train_step(spec, SGD(0.01))
    st = ()
    for _ in range(n):
        x = jnp.asarray(rng.randn(M, B // M, SIZES[0]).astype(np.float32))
        y = jnp.asarray(
            np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, 10, (M, B // M))]
        )
        params, st = step(params, st, x, y)
    return params


def test_round_trip_exact(tmp_path):
    spec = Mo.make_model_spec(SIZES, 1, B)
    params = _train_sequential(jax.tree.map(jnp.asarray, Mo.init_model(spec)), spec)
    p = tmp_path / "ck.npz"
    save_checkpoint(p, params, spec, epoch=3, extra={"note": "t"})
    loaded, spec2, meta = load_checkpoint(p, 1)
    assert meta["epoch"] == 3 and meta["extra"]["note"] == "t"
    assert spec2.sizes == spec.sizes
    for a, b in zip(
        [l for s in params for l in s], [l for s in loaded for l in s]
    ):
        np.testing.assert_array_equal(np.asarray(a["W"]), b["W"])
        np.testing.assert_array_equal(np.asarray(a["b"]).reshape(1, -1), b["b"])


def test_cross_layout_resume_sequential_to_pipeline(tmp_path):
    """Train sequentially, save, resume DP=2 x PP=4 — trained weights must
    land in the right stacked blocks and keep training correctly."""
    spec1 = Mo.make_model_spec(SIZES, 1, B)
    params = _train_sequential(jax.tree.map(jnp.asarray, Mo.init_model(spec1)), spec1)
    p = tmp_path / "ck.npz"
    save_checkpoint(p, params, spec1, epoch=0)

    loaded, spec4, _ = load_checkpoint(p, 4)
    mesh = make_mesh(2, 4)
    stacked, flags = E.put_stacked(*E.stack_params(loaded, spec4), mesh)

    # continue training one batch in BOTH layouts; results must agree
    rng = np.random.RandomState(42)
    xb = rng.randn(B, SIZES[0]).astype(np.float32)
    yb = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, 10, B)]

    step1 = trainer.make_train_step(spec1, SGD(0.01))
    seq_params, _ = step1(
        params,
        (),
        jnp.asarray(xb.reshape(M, B // M, -1)),
        jnp.asarray(yb.reshape(M, B // M, -1)),
    )

    prog = lower_schedule(S.GPipeSchedule, M, 4)
    step4 = E.make_pipeline_step(mesh, spec4, prog, B // 2 // M, SGD(0.01))
    stacked, _, _ = step4(stacked, flags, (), jnp.asarray(xb), jnp.asarray(yb))

    want = [l for s in seq_params for l in s]
    got = [l for s in E.unstack_params(stacked, spec4) for l in s]
    for a, b in zip(want, got):
        np.testing.assert_allclose(np.asarray(a["W"]), b["W"], rtol=3e-4, atol=3e-6)
        np.testing.assert_allclose(
            np.asarray(a["b"]).reshape(-1), b["b"].reshape(-1), rtol=3e-4, atol=3e-6
        )


def test_cross_layout_resume_pipeline_to_sequential(tmp_path):
    mesh = make_mesh(2, 4)
    spec4 = Mo.make_model_spec(SIZES, 4, B)
    prog = lower_schedule(S.GPipeSchedule, M, 4)
    stacked, flags = E.init_stacked(spec4, mesh)
    rng = np.random.RandomState(1)
    xb = rng.randn(B, SIZES[0]).astype(np.float32)
    yb = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, 10, B)]
    step4 = E.make_pipeline_step(mesh, spec4, prog, B // 2 // M, SGD(0.01))
    stacked, _, _ = step4(stacked, flags, (), jnp.asarray(xb), jnp.asarray(yb))

    p = tmp_path / "ck.npz"
    save_checkpoint(p, E.unstack_params(stacked, spec4), spec4, epoch=1)
    loaded, spec1, _ = load_checkpoint(p, 1)

    got = [l for s in loaded for l in s]
    want = [l for s in E.unstack_params(stacked, spec4) for l in s]
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a["W"], b["W"])


def test_save_is_atomic_and_overwrites(tmp_path):
    spec = Mo.make_model_spec(SIZES, 1, B)
    params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
    p = tmp_path / "ck.npz"
    save_checkpoint(p, params, spec, epoch=0)
    save_checkpoint(p, params, spec, epoch=1)  # overwrite path
    _, _, meta = load_checkpoint(p, 1)
    assert meta["epoch"] == 1
    assert not list(tmp_path.glob("*.tmp"))


def test_wrong_stage_count_shape_check(tmp_path):
    spec = Mo.make_model_spec(SIZES, 1, B)
    params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
    p = tmp_path / "ck.npz"
    save_checkpoint(p, params, spec, epoch=0)
    with pytest.raises(ValueError):
        load_checkpoint(p, 3)  # 8 sizes not divisible by 3 stages


# ---------------------------------------------------------------------------
# format v2: error surface, checksum, step cursor, rotation, discovery
# ---------------------------------------------------------------------------


def _params_and_spec():
    spec = Mo.make_model_spec(SIZES, 1, B)
    return jax.tree.map(jnp.asarray, Mo.init_model(spec)), spec


def test_save_failure_never_leaks_a_temp_file(tmp_path, monkeypatch):
    """The mid-stream-failure satellite: an exception between mkstemp and
    the atomic rename must remove the attempt's temp file, whether the
    failure is terminal (non-retried) or exhausts the retry budget."""
    params, spec = _params_and_spec()
    p = tmp_path / "ck.npz"

    def boom(*a, **kw):
        raise RuntimeError("disk detached mid-write")

    monkeypatch.setattr(C.np, "savez", boom)
    with pytest.raises(RuntimeError, match="mid-write"):
        save_checkpoint(p, params, spec, epoch=0)
    assert not p.exists()
    assert list(tmp_path.iterdir()) == []  # no *.npz.tmp beside the target

    # transient OSError: retried with bounded backoff, then the leak-free
    # guarantee still holds when the budget is exhausted
    calls = []

    def flaky(*a, **kw):
        calls.append(1)
        raise OSError("NFS hiccup")

    monkeypatch.setattr(C.np, "savez", flaky)
    monkeypatch.setattr(C.retry.time, "sleep", lambda s: None)
    with pytest.raises(OSError, match="NFS"):
        save_checkpoint(p, params, spec, epoch=0)
    assert len(calls) == 3  # the bounded retry budget, not one attempt
    assert list(tmp_path.iterdir()) == []


def test_save_retries_transient_oserror_then_succeeds(tmp_path, monkeypatch):
    params, spec = _params_and_spec()
    p = tmp_path / "ck.npz"
    real_savez = np.savez
    attempts = []

    def flaky_then_ok(f, **arrays):
        attempts.append(1)
        if len(attempts) < 3:
            raise OSError("transient")
        real_savez(f, **arrays)

    monkeypatch.setattr(C.np, "savez", flaky_then_ok)
    monkeypatch.setattr(C.retry.time, "sleep", lambda s: None)
    nbytes, finite = save_checkpoint(p, params, spec, epoch=2)
    assert len(attempts) == 3
    assert nbytes == p.stat().st_size > 0
    assert finite is True  # healthy params: the retention-gate flag
    assert verify_checkpoint(p)["epoch"] == 2
    assert list(tmp_path.glob("*.tmp")) == []


def test_load_corrupt_files_raise_checkpoint_error(tmp_path):
    """The loader-satellite contract: truncated, zero-byte, wrong-format
    and missing files all surface as CheckpointError naming the path and
    the suspected cause — never a raw NumPy/zipfile traceback."""
    params, spec = _params_and_spec()
    good = tmp_path / "good.npz"
    save_checkpoint(good, params, spec, epoch=0)

    zero = tmp_path / "zero.npz"
    zero.touch()
    with pytest.raises(CheckpointError, match=r"zero\.npz.*zero bytes"):
        load_checkpoint(zero, 1)

    truncated = tmp_path / "trunc.npz"
    truncated.write_bytes(good.read_bytes()[: good.stat().st_size // 2])
    with pytest.raises(CheckpointError, match=r"trunc\.npz.*truncated|corrupt"):
        load_checkpoint(truncated, 1)

    wrong = tmp_path / "wrong.npz"
    wrong.write_text("just some text, not a zip archive\n")
    with pytest.raises(CheckpointError, match=r"wrong\.npz"):
        load_checkpoint(wrong, 1)

    with pytest.raises(CheckpointError, match="cannot stat"):
        load_checkpoint(tmp_path / "missing.npz", 1)

    # a foreign .npz (no metadata blob) is named as such
    foreign = tmp_path / "foreign.npz"
    np.savez(foreign, x=np.zeros(3))
    with pytest.raises(CheckpointError, match="no metadata blob"):
        load_checkpoint(foreign, 1)


def test_checksum_detects_bitflips(tmp_path):
    """The content checksum catches silent corruption the zip layer passes
    through — injected with the fault harness's deterministic byte
    flipper, which stays clear of the archive magic on purpose."""
    params, spec = _params_and_spec()
    p = tmp_path / "ck.npz"
    save_checkpoint(p, params, spec, epoch=0)
    verify_checkpoint(p)  # pristine file verifies
    offsets = faults.corrupt_checkpoint_bytes(p, nbytes=8, seed=1)
    assert offsets and all(o >= 64 for o in offsets)
    with pytest.raises(CheckpointError) as ei:
        verify_checkpoint(p)
    assert "ck.npz" in str(ei.value)


def test_step_cursor_round_trip_and_finiteness_flag(tmp_path):
    """v2 metadata: the step cursor survives the round trip, and a snapshot
    holding non-finite values is flagged at save time and rejected by
    require_finite verification (the halt-flush discovery filter)."""
    params, spec = _params_and_spec()
    p = tmp_path / "ck.npz"
    save_checkpoint(p, params, spec, epoch=3, step_in_epoch=5, global_step=29)
    meta = verify_checkpoint(p, require_finite=True)
    assert meta["epoch"] == 3
    assert meta["step_in_epoch"] == 5 and meta["global_step"] == 29
    assert meta["all_finite"] is True

    bad = [
        [{"W": np.asarray(l["W"]).copy(), "b": np.asarray(l["b"]).copy()}
         for l in s]
        for s in params
    ]
    bad[0][0]["W"][0, 0] = np.nan
    pb = tmp_path / "blown.npz"
    save_checkpoint(pb, bad, spec, epoch=3, step_in_epoch=6, global_step=30)
    assert verify_checkpoint(pb)["all_finite"] is False  # checksum still ok
    with pytest.raises(CheckpointError, match="non-finite"):
        verify_checkpoint(pb, require_finite=True)


def test_rotation_keeps_newest_k(tmp_path):
    params, spec = _params_and_spec()
    for gs in (4, 8, 12, 16):
        save_checkpoint(
            step_checkpoint_path(tmp_path, gs), params, spec,
            epoch=gs // 8, step_in_epoch=gs % 8, global_step=gs,
        )
    removed = rotate_step_checkpoints(tmp_path, keep=2)
    assert sorted(p.name for p in removed) == [
        "step-00000004.npz", "step-00000008.npz"
    ]
    assert [gs for gs, _ in list_step_checkpoints(tmp_path)] == [12, 16]
    with pytest.raises(ValueError):
        rotate_step_checkpoints(tmp_path, keep=0)


def test_rotation_finite_snapshots_outrank_stale_nonfinite_pile(tmp_path):
    """The blown-up-run recovery hazard: a run that diverged without a halt
    leaves high-step non-finite snapshots behind (its own saves skip
    rotation); after resuming from the last healthy snapshot, the fresh
    FINITE snapshots land at lower step numbers than the stale pile. Pure
    step-ranked rotation would keep only the non-finite pile — exactly the
    snapshots resume='auto' skips — so rotation must rank finite first."""
    params, spec = _params_and_spec()
    bad = [
        [{"W": np.asarray(l["W"]).copy(), "b": np.asarray(l["b"]).copy()}
         for l in s]
        for s in params
    ]
    bad[0][0]["W"][0, 0] = np.nan
    # healthy step 4, then the dead run's non-finite grid at 8..20
    save_checkpoint(step_checkpoint_path(tmp_path, 4), params, spec,
                    epoch=0, step_in_epoch=4, global_step=4)
    for gs in (8, 12, 16, 20):
        save_checkpoint(step_checkpoint_path(tmp_path, gs), bad, spec,
                        epoch=0, step_in_epoch=gs, global_step=gs)
    # the resumed run writes a fresh finite snapshot at step 8 (overwriting
    # the stale one) and rotation fires with keep=3
    save_checkpoint(step_checkpoint_path(tmp_path, 8), params, spec,
                    epoch=0, step_in_epoch=8, global_step=8)
    rotate_step_checkpoints(tmp_path, keep=3)
    kept = list_step_checkpoints(tmp_path)
    assert [gs for gs, _ in kept] == [4, 8, 20]  # both finite + newest stale
    path, meta, _ = find_latest_good(tmp_path)
    assert meta["global_step"] == 8  # recovery target survived rotation


def test_rotation_checksum_corrupt_snapshot_cannot_evict_good(tmp_path):
    """The corruption flavor of the crowd-out hazard: a bit-rotted
    high-step snapshot whose zip structure (and meta member) may survive
    must not outrank a verifying one — rotation ranks by the FULL resume
    criteria (checksum + finiteness), not by metadata alone."""
    params, spec = _params_and_spec()
    for gs in (8, 20):
        save_checkpoint(step_checkpoint_path(tmp_path, gs), params, spec,
                        epoch=0, step_in_epoch=gs, global_step=gs)
    faults.corrupt_checkpoint_bytes(step_checkpoint_path(tmp_path, 20))
    removed = rotate_step_checkpoints(tmp_path, keep=1)
    assert [p.name for p in removed] == ["step-00000020.npz"]
    path, meta, _ = find_latest_good(tmp_path)
    assert meta["global_step"] == 8  # the only usable snapshot survived


def test_corrupt_newest_falls_back_to_previous_good(tmp_path):
    """The acceptance criterion: discovery walks newest-first, detects the
    corrupted newest snapshot via its checksum, and lands on the previous
    good one — reporting the skip with its cause."""
    params, spec = _params_and_spec()
    for gs in (4, 8, 12):
        save_checkpoint(
            step_checkpoint_path(tmp_path, gs), params, spec,
            epoch=0, step_in_epoch=gs, global_step=gs,
        )
    newest = step_checkpoint_path(tmp_path, 12)
    faults.corrupt_checkpoint_bytes(newest, nbytes=8, seed=3)
    path, meta, skipped = find_latest_good(tmp_path)
    assert path == step_checkpoint_path(tmp_path, 8)
    assert meta["global_step"] == 8
    assert [p for p, _ in skipped] == [newest]
    assert skipped[0][1]  # a human-readable cause rides along

    # empty / missing directory: a fresh start, not an error
    assert find_latest_good(tmp_path / "nope") == (None, None, [])
    # nothing verifies: (None, None, every-candidate-with-cause)
    for gs in (4, 8):
        faults.corrupt_checkpoint_bytes(
            step_checkpoint_path(tmp_path, gs), nbytes=8, seed=gs
        )
    path, meta, skipped = find_latest_good(tmp_path)
    assert path is None and meta is None and len(skipped) == 3


# ---------------------------------------------------------------------------
# the async writer (docs/robustness.md "The async writer's crash windows")
# ---------------------------------------------------------------------------


def _snapshot_job(step=0, value=1.0):
    """A tiny stage-1 (arrays, meta) pair the writer tests feed in."""
    spec = Mo.make_model_spec((4, 3, 2), 1, 4)
    params = [
        [
            {"W": np.full((3, 4), value, np.float32),
             "b": np.zeros((1, 3), np.float32)},
            {"W": np.full((2, 3), value, np.float32),
             "b": np.zeros((1, 2), np.float32)},
        ]
    ]
    return C.build_snapshot(
        params, spec, epoch=0, step_in_epoch=step % 1, global_step=step
    )


def test_async_writer_writes_in_order_and_drains(tmp_path):
    """Jobs rename into place in submit order, drain() blocks until every
    snapshot is durable, and each completion callback carries the
    verify/write timings plus the stamped finiteness flag."""
    results = []
    w = C.AsyncCheckpointWriter(max_in_flight=2)
    for step in (1, 2, 3):
        arrays, meta = _snapshot_job(step)
        w.submit(
            step_checkpoint_path(tmp_path, step), arrays, meta, step,
            on_complete=results.append,
        )
    w.drain()
    assert [gs for gs, _ in list_step_checkpoints(tmp_path)] == [1, 2, 3]
    assert [r["meta"]["global_step"] for r in results] == [1, 2, 3]
    assert all(
        r["all_finite"] and r["bytes"] > 0
        and r["verify_s"] >= 0 and r["write_s"] >= 0
        for r in results
    )
    # every renamed file fully verifies — the writer's whole point
    for _, p in list_step_checkpoints(tmp_path):
        verify_checkpoint(p, require_finite=True)
    w.close()
    w.close()  # idempotent


def test_async_writer_bounded_queue_applies_backpressure(tmp_path):
    """submit() BLOCKS when max_in_flight jobs are pending — a snapshot is
    never dropped to keep the step path fast. A slow@save injection
    stalls the writer inside the write window; the 3rd submit can only
    return after the stalled job vacates the queue."""
    import time as _time

    plan = faults.FaultPlan.parse("slow@save=0:ms=300")
    w = C.AsyncCheckpointWriter(max_in_flight=1, faults=plan)
    arrays, meta = _snapshot_job(1)
    w.submit(step_checkpoint_path(tmp_path, 1), arrays, meta, 0)
    arrays, meta = _snapshot_job(2)
    w.submit(step_checkpoint_path(tmp_path, 2), arrays, meta, 1)
    t0 = _time.perf_counter()
    arrays, meta = _snapshot_job(3)
    w.submit(step_checkpoint_path(tmp_path, 3), arrays, meta, 2)
    blocked = _time.perf_counter() - t0
    w.drain()
    assert blocked > 0.05, "full queue did not block the submitter"
    assert [gs for gs, _ in list_step_checkpoints(tmp_path)] == [1, 2, 3]
    assert plan.faults[0].fired
    w.close()


def test_async_writer_die_in_window_leaves_no_visible_torn_file(tmp_path):
    """die@save (exc mode in-process; sigkill is the subprocess shape)
    fires AFTER the temp write, BEFORE the rename: the victim snapshot is
    never rename-visible, older snapshots stay fully-verifying, and the
    failure re-raises on the submitting thread at drain()."""
    plan = faults.FaultPlan.parse("die@save=1")
    w = C.AsyncCheckpointWriter(max_in_flight=2, faults=plan)
    for seq, step in enumerate((4, 8)):
        arrays, meta = _snapshot_job(step)
        w.submit(step_checkpoint_path(tmp_path, step), arrays, meta, seq)
    with pytest.raises(faults.InjectedFault, match="die@save=1"):
        w.drain()
    # save 0 (step 4) is durable and verifying; save 1 (step 8) never
    # renamed — discovery cannot see anything torn
    assert [gs for gs, _ in list_step_checkpoints(tmp_path)] == [4]
    p, meta, skipped = find_latest_good(tmp_path)
    assert p.name == "step-00000004.npz" and skipped == []
    w.close()


def test_corrupt_save_injection_renames_but_never_verifies(tmp_path):
    """corrupt@save flips the in-flight buffer AFTER the checksum stamp:
    the file lands rename-visible but fails verification, and discovery
    falls back past it to the previous good snapshot — the exact bit-rot
    shape the chaos harness needs without racing the writer."""
    plan = faults.FaultPlan.parse("corrupt@save=1")
    w = C.AsyncCheckpointWriter(max_in_flight=2, faults=plan)
    for seq, step in enumerate((4, 8)):
        arrays, meta = _snapshot_job(step)
        w.submit(step_checkpoint_path(tmp_path, step), arrays, meta, seq)
    w.drain()
    assert [gs for gs, _ in list_step_checkpoints(tmp_path)] == [4, 8]
    p, meta, skipped = find_latest_good(tmp_path)
    assert p.name == "step-00000004.npz"
    assert len(skipped) == 1 and "checksum" in skipped[0][1]
    w.close()


def test_async_writer_rotation_runs_after_rename(tmp_path):
    """Rotation is armed per job and runs strictly AFTER the new snapshot
    is durable — retention converges to keep while every survivor
    verifies."""
    w = C.AsyncCheckpointWriter(max_in_flight=2)
    for seq, step in enumerate((1, 2, 3, 4)):
        arrays, meta = _snapshot_job(step)
        w.submit(
            step_checkpoint_path(tmp_path, step), arrays, meta, seq,
            rotate_dir=tmp_path, rotate_keep=2,
        )
    w.drain()
    assert [gs for gs, _ in list_step_checkpoints(tmp_path)] == [3, 4]
    w.close()


def test_sync_and_async_saves_produce_identical_files(tmp_path):
    """The shared-stages contract: the synchronous path and the writer
    produce byte-wise interchangeable snapshots (same arrays, same
    checksum) — the crash-consistency analysis covers both because they
    ARE the same code."""
    arrays_a, meta_a = _snapshot_job(5)
    arrays_b, meta_b = _snapshot_job(5)
    sync_p = step_checkpoint_path(tmp_path / "sync", 5)
    C.run_save_stages(sync_p, arrays_a, meta_a)
    w = C.AsyncCheckpointWriter(max_in_flight=1)
    async_p = step_checkpoint_path(tmp_path / "async", 5)
    w.submit(async_p, arrays_b, meta_b, 0)
    w.drain()
    w.close()
    ma = verify_checkpoint(sync_p)
    mb = verify_checkpoint(async_p)
    assert ma["checksum"] == mb["checksum"]
    assert ma == mb


# ---------------------------------------------------------------------------
# single-verified-read discovery (with_arrays)
# ---------------------------------------------------------------------------


def test_find_latest_good_with_arrays_is_one_read(tmp_path, monkeypatch):
    """with_arrays=True returns the arrays of the SAME read discovery
    verified, and assemble_checkpoint loads from them without touching
    the file again — pinned by counting _read_arrays calls and by
    deleting the file between discovery and assembly (the TOCTOU window
    that used to need a second read is gone by construction)."""
    params, spec = _params_and_spec()
    p = step_checkpoint_path(tmp_path, 3)
    save_checkpoint(p, params, spec, epoch=0, step_in_epoch=0, global_step=3)
    reads = []
    real = C._read_arrays

    def counting(path):
        reads.append(str(path))
        return real(path)

    monkeypatch.setattr(C, "_read_arrays", counting)
    path, meta, arrays, skipped = find_latest_good(tmp_path, with_arrays=True)
    assert path == p and skipped == []
    assert len(reads) == 1
    p.unlink()  # nothing re-reads it: the TOCTOU window is closed
    loaded, lspec, lmeta = C.assemble_checkpoint(path, meta, arrays, 1)
    assert len(reads) == 1  # still one read, assembly touched no file
    assert lmeta["global_step"] == 3
    for a, b in zip(
        [l for st in params for l in st], [l for st in loaded for l in st]
    ):
        np.testing.assert_array_equal(np.asarray(a["W"]), b["W"])


def test_find_newer_good_with_arrays(tmp_path):
    params, spec = _params_and_spec()
    for gs in (2, 4):
        save_checkpoint(
            step_checkpoint_path(tmp_path, gs), params, spec,
            epoch=0, step_in_epoch=0, global_step=gs,
        )
    step, path, meta, arrays, skipped = C.find_newer_good(
        tmp_path, than_step=2, with_arrays=True
    )
    assert step == 4 and meta["global_step"] == 4 and "w0" in arrays
    step, path, meta, arrays, skipped = C.find_newer_good(
        tmp_path, than_step=4, with_arrays=True
    )
    assert step is None and arrays is None
