"""Slot geometry for inference dispatch: the fixed microbatch grid + ladder.

Serving packs variable-size requests into the pipeline executor's microbatch
slots — the same on-the-fly packing torchgpipe applies to training
microbatches (arXiv 2004.09910). Two constants fix the whole geometry:

- ``slot_rows``   the GLOBAL row count of one microbatch slot (divisible by
                  dp; each replica computes ``slot_rows / dp`` rows of it).
                  Every inference dispatch is a whole number of slots, and
                  every request occupies a whole number of slots — requests
                  never share a slot, so a request's per-slot inputs are
                  identical whether it rides alone or packed with others;
- ``slot ladder`` the allowed slot counts per dispatch (default 1, 2, 4, 8,
                  16). A dispatch's slot count is rounded UP to the next
                  rung, so the number of distinct compiled inference
                  programs is bounded by ``len(ladder)`` — the fix for the
                  unbounded one-program-per-row-count predict cache.

Why fixed slots instead of one variable-size padded batch: XLA tiles a
matmul by its SHAPE, so the same row computed inside a (8, d) and a (64, d)
batch differs at ULP level (measured on the CPU backend). With a fixed slot
shape, every slot is the same compiled compute regardless of which rung
program or slot position it rides in — measured bitwise-identical — which is
what lets the serving engine promise responses bitwise-equal to a direct
``predict()`` of the same rows.

Layout: the executor shards the global batch row-contiguously over ``dp``
and then reshapes each replica's block into ``(num_slots, slot_rows/dp)``
microbatches, so logical slot ``m`` is NOT ``rows[m*S:(m+1)*S]`` of the
global array — it is ``slot_rows/dp`` consecutive rows from EACH replica's
block. ``pack_slots``/``unpack_slots`` are the one definition of that
mapping (api.predict and the tests share it).
"""

import numpy as np

# slot counts per dispatch — geometric so low load pays small dispatches and
# the compile count stays bounded at len(ladder) programs per layout
DEFAULT_SLOT_LADDER = (1, 2, 4, 8, 16)

# target global rows per slot before rounding up to a dp multiple
DEFAULT_SLOT_ROWS = 8


def default_slot_rows(dp, target=DEFAULT_SLOT_ROWS):
    """The default slot height: ``target`` rounded up to a dp multiple."""
    return -(-int(target) // int(dp)) * int(dp)


def validate_ladder(ladder):
    """-> the ladder as a strictly-increasing tuple of positive ints."""
    ladder = tuple(int(r) for r in ladder)
    if not ladder or any(r < 1 for r in ladder):
        raise ValueError(f"slot ladder must be positive ints, got {ladder!r}")
    if any(b <= a for a, b in zip(ladder, ladder[1:])):
        raise ValueError(f"slot ladder must be strictly increasing: {ladder!r}")
    return ladder


def slots_needed(n_rows, slot_rows):
    """Slots a request of ``n_rows`` rows occupies (requests never share a
    slot — the bitwise-parity contract needs per-request slot contents)."""
    if n_rows < 1:
        raise ValueError("a request needs at least one row")
    return -(-int(n_rows) // int(slot_rows))


def rung_for(n_slots, ladder):
    """The smallest ladder rung >= ``n_slots`` (callers chunk by the top
    rung first, so ``n_slots`` never exceeds it)."""
    for r in ladder:
        if r >= n_slots:
            return r
    raise ValueError(
        f"{n_slots} slots exceed the ladder's top rung {ladder[-1]} — "
        "chunk the dispatch first"
    )


def pack_slots(slots, dp):
    """Logical slots -> the executor's global row layout.

    ``slots``: (M, slot_rows, d) array of logical slot contents. Returns
    (M * slot_rows, d): replica r's contiguous block holds rows
    ``[r*S/dp : (r+1)*S/dp)`` of every slot, in slot order — exactly what
    ``x.reshape(M, slot_rows/dp, d)`` per replica undoes on device.
    """
    slots = np.asarray(slots)
    M, S, d = slots.shape
    if S % dp:
        raise ValueError(f"slot_rows {S} not divisible by dp {dp}")
    return (
        slots.reshape(M, dp, S // dp, d)
        .transpose(1, 0, 2, 3)
        .reshape(M * S, d)
    )


def unpack_slots(arr, num_slots, dp):
    """Inverse of ``pack_slots`` for the dispatch's outputs: the executor's
    global row layout -> (num_slots * slot_rows, d) in logical slot order."""
    arr = np.asarray(arr)
    rows, d = arr.shape
    S = rows // num_slots
    return (
        arr.reshape(dp, num_slots, S // dp, d)
        .transpose(1, 0, 2, 3)
        .reshape(rows, d)
    )
