"""shallowspeed_tpu — a TPU-native distributed-training framework.

A brand-new JAX/XLA re-design of the capabilities of siboehm/ShallowSpeed
(reference mounted at /root/reference): deep-MLP training on MNIST under
sequential, data-parallel (DP), pipeline-parallel (PP — naive / GPipe /
PipeDream-Flush / interleaved virtual-stage schedules) and composed DP x PP
layouts, with SGD / momentum / Adam optimizers, optional ZeRO-1
optimizer-state sharding, and layout-independent checkpoints (optimizer
state included). The one-object entry point is
``shallowspeed_tpu.api.TrainingSession``.

Architecture (TPU-first, not a port):

- ``ops``        pure jax.numpy forward + hand-written backward kernels
                 (the reference keeps these in NumPy: functional.py).
- ``model``      stage partitioning + explicit forward/backward over a params
                 pytree with residuals threaded explicitly (the reference uses
                 stateful Module._cache dicts: layers.py).
- ``schedules``  pipeline schedules as pure instruction-stream generators
                 (same load-bearing abstraction as reference pipe.py:141-299).
- ``parallel``   the TPU execution layer: a schedule -> clock-tick *lowering*
                 (MPMD instruction streams compiled to a static SPMD tick
                 program) and a shard_map executor over a 2-D (dp, pp)
                 jax.sharding.Mesh where jax.lax.ppermute replaces MPI
                 Send/Recv and jax.lax.psum replaces Iallreduce.
- ``data``       the MNIST-784 parquet/npy data layer with strided DP sharding
                 and microbatch slicing (reference dataset.py semantics).
- ``optimizer``  SGD / momentum / Adam over pytrees, applied on-device inside
                 the jitted step; the ``state_layout`` protocol carries any
                 optimizer state through checkpoints, stacked pp sharding and
                 ZeRO-1 chunking.
- ``checkpoint`` layout-independent .npz save/resume (params + opt state).
- ``observability`` training telemetry: metrics recorders (versioned JSONL /
                 in-memory / null), profiling spans wrapping
                 jax.profiler.TraceAnnotation, and the chrome-trace
                 analyzer behind docs/performance.md's roofline numbers.
- ``api``        ``TrainingSession`` — data + model + layout + optimizer +
                 eval as one object (the CLI in train.py is a thin wrapper);
                 ``metrics=`` streams per-epoch telemetry + spans.
"""

from shallowspeed_tpu import (
    checkpoint,
    data,
    model,
    ops,
    optimizer,
    schedules,
    trainer,
    utils,
)
from shallowspeed_tpu.model import ModelSpec, StageSpec, init_model, partition_sizes

__version__ = "0.1.0"
