"""Static program analysis tests: the tick-table passes prove every
lowered schedule clean, tampered tables are refused naming the offending
tick, the MPMD deadlock proof catches cyclic waits the lockstep executor
could never exhibit, the HLO donation pass refuses donating executables
on dispatch paths, and the session wires it all in at lowering/compile
time (schema-v9 static_analysis records)."""

import dataclasses

import numpy as np
import pytest

from shallowspeed_tpu import schedules as S
from shallowspeed_tpu.analysis import (
    ProgramAnalysisError,
    analyze_program,
    check_deadlock_free,
    check_send_recv,
    check_stash_lifetime,
)
from shallowspeed_tpu.observability import program_audit
from shallowspeed_tpu.parallel.lowering import OP_FWD, lower_schedule

LOWERINGS = (
    ("naive", S.NaiveParallelSchedule, {}),
    ("gpipe", S.GPipeSchedule, {}),
    ("pipedream", S.PipeDreamFlushSchedule, {}),
    ("gpipe-split", S.GPipeSchedule, {"backward_split": True}),
    ("pipedream-split", S.PipeDreamFlushSchedule, {"backward_split": True}),
    ("interleaved-v2", S.InterleavedSchedule, {"virtual": 2}),
    ("inference", S.InferenceSchedule, {"training": False}),
    (
        "inference-interleaved",
        S.InterleavedInferenceSchedule,
        {"training": False, "virtual": 2},
    ),
)


@pytest.mark.parametrize(
    "name,cls,kw", LOWERINGS, ids=[c[0] for c in LOWERINGS]
)
@pytest.mark.parametrize("M,P", [(4, 4), (8, 4), (4, 2)])
def test_every_lowered_schedule_analyzes_clean(name, cls, kw, M, P):
    """The analyzer independently re-proves what the lowering simulator
    constructs: every send consumed on the peer, deadlock-free under
    async dispatch, stash lifetimes exact. Clean across the whole
    schedule x size lattice."""
    prog = lower_schedule(cls, M, P, **kw)
    verdict = analyze_program(prog, program=name)
    assert verdict["findings"] == 0
    assert verdict["passes"] == ["send_recv", "deadlock", "stash"]
    # sends on the wire == sends consumed (the replay popped every one)
    sends = verdict["send_recv"]
    assert sends["sends_fwd"] == int(np.sum(prog.send_fwd))
    assert sends["sends_bwd"] == int(np.sum(prog.send_bwd))
    # the measured stash peak IS the allocated depth (training only)
    if prog.is_training:
        assert verdict["stash"]["stash"]["peak"] == prog.n_stash_slots
        if prog.backward_split:
            assert verdict["stash"]["gstash"]["peak"] == prog.n_gstash_slots
    # every message edge found a matched sender
    assert verdict["deadlock"]["message_edges"] == (
        sends["sends_fwd"] + sends["sends_bwd"]
    )


def test_pp1_inference_program_is_trivially_clean():
    prog = lower_schedule(S.InferenceSchedule, 2, 1, training=False)
    v = analyze_program(prog, program="pp1")
    assert v["send_recv"]["sends_fwd"] == 0
    assert v["stash"]["stash"]["writes"] == 0


# -- tampered tables are refused, naming the tick ---------------------------


def _gpipe():
    return lower_schedule(S.GPipeSchedule, 4, 4)


def test_unmatched_send_refused_with_tick_named():
    """Dropping a consuming read leaves its message undelivered forever:
    the send's slot is clobbered by the next delivery (or left occupied
    at end) — refused naming tick/stage/slot."""
    base = _gpipe()
    rf = np.array(base.read_fwd_slot)
    t, s = np.argwhere(rf != base.n_fwd_slots)[0]
    rf[t, s] = base.n_fwd_slots
    with pytest.raises(ProgramAnalysisError, match=r"tick \d+ stage \d+"):
        check_send_recv(dataclasses.replace(base, read_fwd_slot=rf))


def test_recv_with_no_send_refused():
    """A read of an empty mailbox slot (recv with no matching send)."""
    base = _gpipe()
    rf = np.array(base.read_fwd_slot)
    assert rf[0, 2] == base.n_fwd_slots  # stage 2 idles at tick 0
    rf[0, 2] = 0
    with pytest.raises(ProgramAnalysisError, match="no message"):
        check_send_recv(dataclasses.replace(base, read_fwd_slot=rf))


def test_phantom_delivery_refused():
    base = _gpipe()
    inf = np.array(base.in_fwd_slot)
    # claim a delivery on a tick whose upstream stage sends nothing
    t, dst = None, None
    for tt in range(base.num_ticks):
        for d in range(base.num_stages):
            src = (d - 1) % base.num_stages
            if not base.send_fwd[tt, src] and inf[tt, d] == base.n_fwd_slots:
                t, dst = tt, d
                break
        if t is not None:
            break
    inf[t, dst] = 0
    with pytest.raises(ProgramAnalysisError, match="phantom"):
        check_send_recv(dataclasses.replace(base, in_fwd_slot=inf))


def test_stash_leak_refused():
    base = _gpipe()
    sr = np.array(base.stash_read)
    t, s = np.argwhere(sr != base.n_stash_slots)[-1]
    sr[t, s] = base.n_stash_slots
    with pytest.raises(ProgramAnalysisError, match="leaked stash slot"):
        check_stash_lifetime(dataclasses.replace(base, stash_read=sr))


def test_stash_read_before_write_refused():
    base = _gpipe()
    sr = np.array(base.stash_read)
    assert base.op[0, 3] == 0  # the last stage idles at tick 0
    sr[0, 3] = 0
    with pytest.raises(ProgramAnalysisError, match="read before write"):
        check_stash_lifetime(dataclasses.replace(base, stash_read=sr))


def test_stash_double_write_refused():
    base = _gpipe()
    sw = np.array(base.stash_write)
    writes = np.argwhere(sw != base.n_stash_slots)
    # make the second write on stage 0 claim the first write's slot
    (t0, s0), (t1, s1) = writes[0], writes[writes[:, 1] == writes[0][1]][1]
    sw[t1, s1] = sw[t0, s0]
    with pytest.raises(ProgramAnalysisError, match="double write"):
        check_stash_lifetime(dataclasses.replace(base, stash_write=sw))


def test_stash_peak_mismatch_refused():
    """Tables intact but the allocated depth padded: the exact-peak leg
    catches buffers not sized to the schedule's true pressure. (The
    trash sentinel is the depth itself, so padding the depth remaps
    every trash cell too.)"""
    base = _gpipe()
    old, new = base.n_stash_slots, base.n_stash_slots + 1
    remap = {}
    for name in ("stash_write", "stash_read", "stash_peek"):
        tab = np.array(getattr(base, name))
        tab[tab == old] = new
        remap[name] = tab
    with pytest.raises(ProgramAnalysisError, match="peak"):
        check_stash_lifetime(
            dataclasses.replace(base, n_stash_slots=new, **remap)
        )


def test_recompute_peak_drop_proved_from_tick_tables():
    """The smoke-gate proof: gpipe's recompute twin measurably drops the
    live residual-stash peak (M slots -> 1) — measured by replaying the
    tick tables, not by reading allocation metadata."""
    from shallowspeed_tpu.analysis.stash import assert_recompute_peak_drop

    stashed = lower_schedule(S.GPipeSchedule, 4, 4)
    rec = lower_schedule(S.GPipeSchedule, 4, 4, recompute=True)
    out = assert_recompute_peak_drop(stashed, rec)
    assert out["stash_peak_stashed"] == 4
    assert out["stash_peak_recompute"] == 1
    assert out["xin_peak"] >= 1


def test_recompute_peak_drop_honest_floor_of_one():
    """naive holds one live stash slot at peak either way — nothing to
    reclaim; the proof accepts the floor instead of demanding a
    dishonest drop."""
    from shallowspeed_tpu.analysis.stash import assert_recompute_peak_drop

    stashed = lower_schedule(S.NaiveParallelSchedule, 4, 4)
    rec = lower_schedule(S.NaiveParallelSchedule, 4, 4, recompute=True)
    out = assert_recompute_peak_drop(stashed, rec)
    assert out["stash_peak_stashed"] == 1
    assert out["stash_peak_recompute"] == 1


def test_recompute_peak_drop_refuses_mislabelled_twins():
    """Handing the proof two stashed programs (or twins in the wrong
    order) is refused before any replay — the comparison is only
    meaningful between a stashed program and ITS recompute twin."""
    from shallowspeed_tpu.analysis.stash import assert_recompute_peak_drop

    stashed = lower_schedule(S.GPipeSchedule, 4, 4)
    rec = lower_schedule(S.GPipeSchedule, 4, 4, recompute=True)
    with pytest.raises(ProgramAnalysisError, match="not a recompute"):
        assert_recompute_peak_drop(stashed, stashed)
    with pytest.raises(ProgramAnalysisError, match="must be the"):
        assert_recompute_peak_drop(rec, rec)


def test_recompute_peak_drop_refuses_non_dropping_program():
    """A 'recompute' program whose tables still hold the stashed twin's
    lifetime (flag flipped, tables untouched) fails the strict-drop
    leg with the two peaks named."""
    from shallowspeed_tpu.analysis.stash import assert_recompute_peak_drop

    stashed = lower_schedule(S.GPipeSchedule, 4, 4)
    fake = dataclasses.replace(stashed, recompute=True)
    with pytest.raises(ProgramAnalysisError, match="did not shorten"):
        assert_recompute_peak_drop(stashed, fake)


def test_cyclic_wait_refused_naming_the_chain():
    """Two single-cell stages each consuming the other's send: no
    lockstep tick order can realize it, and the async-dispatch proof
    names the literal wait chain."""
    base = _gpipe()
    one = np.ones((1, 2), np.int32)
    zero = np.zeros((1, 2), np.int32)
    cyclic = dataclasses.replace(
        base,
        num_ticks=1, num_stages=2, num_micro_batches=1,
        n_fwd_slots=1, n_bwd_slots=1,
        op=np.full((1, 2), OP_FWD, np.int32), mb=zero,
        read_fwd_slot=np.array([[1, 0]], np.int32),
        read_bwd_slot=np.array([[0, 1]], np.int32),
        in_fwd_slot=np.array([[1, 0]], np.int32),
        in_bwd_slot=np.array([[0, 1]], np.int32),
        send_fwd=np.array([[1, 0]], np.int32),
        send_bwd=np.array([[0, 1]], np.int32),
        stash_write=one, stash_read=one, stash_peek=one,
        gstash_write=zero, gstash_read=zero,
        chunk=zero, load_in=zero, is_head=zero,
    )
    with pytest.raises(ProgramAnalysisError, match="cyclic wait") as ei:
        check_deadlock_free(cyclic)
    assert "stage 0 tick 0" in str(ei.value)
    assert "stage 1 tick 0" in str(ei.value)


def test_deadlock_pass_is_tick_free():
    """The deadlock proof must not secretly rely on tick numbers: a
    healthy program with every tick REVERSED in per-stage order is a
    DIFFERENT dispatch order but the same key-matched message structure
    — the send/recv replay refuses it (tick semantics), while the
    key-based matching still resolves every message (no 'unmatched'
    refusal from the deadlock pass's matcher on the original)."""
    base = _gpipe()
    stats = check_deadlock_free(base)
    assert stats["message_edges"] == int(
        np.sum(base.send_fwd) + np.sum(base.send_bwd)
    )
    assert stats["reuse_edges"] >= 0


# -- HLO donation / dispatch safety -----------------------------------------


SYNTH_HEADER = (
    "HloModule jit_step, is_scheduled=true, input_output_alias={ {0}: "
    "(0, {}, may-alias), {1,0}: (2, {1}, must-alias) }, "
    "entry_computation_layout={(f32[4]{0})->f32[4]{0}}"
)


def test_parse_input_output_aliases_synthetic():
    aliases = program_audit.parse_input_output_aliases(SYNTH_HEADER)
    assert aliases == [
        {"output_index": [0], "param_number": 0, "param_index": [],
         "kind": "may-alias"},
        {"output_index": [1, 0], "param_number": 2, "param_index": [1],
         "kind": "must-alias"},
    ]
    census = program_audit.donation_census(SYNTH_HEADER)
    assert census == {
        "aliased_outputs": 2,
        "donated_params": [0, 2],
        "kinds": {"may-alias": 1, "must-alias": 1},
    }
    assert program_audit.parse_input_output_aliases("HloModule clean") == []


def test_dispatch_safety_refuses_real_donating_executable():
    import jax
    import jax.numpy as jnp

    donating = (
        jax.jit(lambda a, b: (a + b, a * b), donate_argnums=(0,))
        .lower(jnp.zeros((4, 4)), jnp.ones((4, 4)))
        .compile()
    )
    with pytest.raises(
        program_audit.AuditMismatchError, match="input_output_alias"
    ):
        program_audit.verify_dispatch_safety(donating, context="rung")
    clean = (
        jax.jit(lambda a, b: (a + b, a * b))
        .lower(jnp.zeros((4, 4)), jnp.ones((4, 4)))
        .compile()
    )
    census = program_audit.verify_dispatch_safety(clean, context="rung")
    assert census["aliased_outputs"] == 0
    # text input works too, and the refusal names the context
    with pytest.raises(program_audit.AuditMismatchError, match="rung"):
        program_audit.verify_dispatch_safety(SYNTH_HEADER, context="rung")


# -- session wiring ---------------------------------------------------------


SIZES = (24, 20, 18, 16, 14, 12, 11, 10)


@pytest.fixture()
def data_dir(tmp_path):
    rng = np.random.RandomState(0)
    for suffix, n in (("train", 256), ("val", 64)):
        x = rng.randn(n, SIZES[0]).astype(np.float32)
        y = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], n)]
        np.save(tmp_path / f"x_{suffix}.npy", x)
        np.save(tmp_path / f"y_{suffix}.npy", y)
    return tmp_path


class _Rec:
    """Minimal enabled recorder capturing raw records."""

    enabled = True

    def __init__(self):
        from shallowspeed_tpu.observability import MetricsRecorder

        class R(MetricsRecorder):
            def __init__(self):
                super().__init__()
                self.records = []

            def _emit(self, rec):
                self.records.append(rec)

        self.r = R()


def test_session_records_static_analysis_at_lowering_and_serving(data_dir):
    """audit=True + metrics: the epoch program's static passes run at
    construction (before any compile), the serving rung's at its first
    predict — both recorded as clean schema-v9 static_analysis verdicts,
    and the report CLI folds them into the Static checks row."""
    from shallowspeed_tpu.api import TrainingSession
    from shallowspeed_tpu.observability.report import build_report, render

    m = _Rec().r
    sess = TrainingSession(
        sizes=SIZES, pp=2, schedule="gpipe", mubatches=2,
        global_batch_size=32, data_dir=data_dir, metrics=m, audit=True,
    )
    sa = [r for r in m.records if r["kind"] == "static_analysis"]
    assert [r["name"] for r in sa] == ["epoch_program"]
    assert sa[0]["findings"] == 0
    assert sa[0]["passes"] == ["send_recv", "deadlock", "stash"]
    assert sa[0]["stash"]["stash"]["peak"] == sa[0]["stash"]["stash_slots"]
    rng = np.random.RandomState(1)
    sess.predict(rng.rand(sess.slot_rows, SIZES[0]).astype(np.float32))
    sa = [r for r in m.records if r["kind"] == "static_analysis"]
    assert [r["name"] for r in sa] == ["epoch_program", "inference_r1"]
    assert all(r["findings"] == 0 for r in sa)
    report = build_report(sa, source="test")
    assert report["static_analysis"]["programs"] == [
        "epoch_program", "inference_r1",
    ]
    text = render(report, "md")
    assert "static checks" in text
    assert "2 program(s) clean" in text


def test_report_renders_static_finding(tmp_path):
    """A refused program's evidence record renders as a finding row."""
    from shallowspeed_tpu.observability.report import build_report, render

    recs = [
        {
            "v": 9, "kind": "static_analysis", "name": "epoch_program",
            "passes": ["send_recv", "deadlock", "stash"], "findings": 1,
            "finding": "tick 3 stage 1: reads fwd mailbox slot 0 which"
                       " holds no message",
        }
    ]
    text = render(build_report(recs, source="t"), "md")
    assert "static checks" in text
    assert "1 finding(s)" in text and "tick 3" in text


def test_report_renders_lint_record_with_full_evidence():
    """A lint-run record (finding_lines, plural count) renders its real
    count and every finding line — not an unnamed singular."""
    from shallowspeed_tpu.observability.report import build_report, render

    recs = [
        {
            "v": 9, "kind": "static_analysis", "name": "lint",
            "passes": ["BLE001", "SSP004"], "findings": 2,
            "by_rule": {"BLE001": 1, "SSP004": 1},
            "finding_lines": [
                "a.py:7:4: BLE001 broad except that swallows",
                "b.py:5:11: SSP004 donate_argnums outside the whitelist",
            ],
        }
    ]
    report = build_report(recs, source="t")
    assert report["static_analysis"]["findings"] == 2
    text = render(report, "md")
    assert "2 finding(s)" in text
    assert "a.py:7:4" in text and "b.py:5:11" in text


def test_aot_deserialized_donating_program_refused(data_dir, tmp_path):
    """The PR 1/PR 12 hazard as a proven property: poison an AOT cache
    entry for a DISPATCH-path program with a donating executable — the
    load is refused (audit_mismatch + fallback recompile), the serving
    path never dispatches it, and predictions stay correct."""
    import jax
    import jax.numpy as jnp

    from shallowspeed_tpu.api import TrainingSession
    from shallowspeed_tpu.observability import MetricsRecorder

    cache = tmp_path / "aot"
    m = _Rec().r
    sess = TrainingSession(
        sizes=SIZES, dp=2, mubatches=2, global_batch_size=32,
        data_dir=data_dir, metrics=m, audit=True, aot_cache_dir=str(cache),
    )
    if not sess._aot.supported:
        pytest.skip(f"backend cannot serialize: {sess._aot.disabled_reason}")
    rng = np.random.RandomState(2)
    X = rng.rand(sess.slot_rows, SIZES[0]).astype(np.float32)
    ref = sess.predict(X)
    assert sess._aot.counts["store"] >= 1
    # replace the stored rung entry with a DONATING executable under the
    # same key (what a buggy writer — or the pre-PR-13 trust model —
    # could have left there)
    entries = sorted(cache.glob("*.aotx"))
    assert entries
    donating = (
        jax.jit(lambda a, b: (a + b, a * b), donate_argnums=(0,))
        .lower(jnp.zeros((4, 4)), jnp.ones((4, 4)))
        .compile()
    )
    for e in entries:
        key = e.stem
        e.unlink()
        sess._aot.store(key, donating, program="poisoned")
    # a fresh session over the poisoned cache must refuse the entry and
    # recompile — never dispatch the donating executable
    m2 = _Rec().r
    sess2 = TrainingSession(
        sizes=SIZES, dp=2, mubatches=2, global_batch_size=32,
        data_dir=data_dir, metrics=m2, audit=True, aot_cache_dir=str(cache),
    )
    out = sess2.predict(X)
    counts = sess2._aot.counts
    assert counts["audit_mismatch"] >= 1, counts
    assert counts["fallback"] >= 1
    events = [
        r for r in m2.records
        if r["kind"] == "aot_cache" and r["name"] == "audit_mismatch"
    ]
    assert events
    assert np.array_equal(out, ref)
