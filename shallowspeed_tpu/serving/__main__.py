"""Serve entry point: wire a checkpoint to an engine — or a FLEET — and
drive it.

    python -m shallowspeed_tpu.serving [--dp N] [--pp M] [--schedule gpipe]
        [--checkpoint ck.npz] [--requests 200] [--rate 100] [--seed 0]
        [--slo-ms 50] [--verify] [--audit] [--metrics-out serve.jsonl]
        [--faults SPEC] [--retry-budget 2] [--breaker 3]
        [--fleet N] [--fleet-policy least_queue|p2c] [--fleet-retry 2]

Builds a ``TrainingSession`` on the requested layout (restoring
``--checkpoint`` through the PR6 loader when given — any saved layout serves
on any serving layout), wraps it in a ``ServingEngine``, and drives seeded
Poisson load through it in open- or closed-loop mode. ``--audit`` verifies
every compiled inference program's collective census against the
forward-only serving contract before it serves a request; ``--verify``
re-computes every ``"ok"`` response with a direct ``session.predict()`` of
the same rows and demands bitwise equality — the ``make serve-smoke``
contract. ``--faults`` injects the chaos plan (``@dispatch=`` grammar,
docs/robustness.md; also read from ``SHALLOWSPEED_FAULTS``, so a
subprocess can be killed without patching it). The loadgen drivers are
the operator loop: an injected ``die`` (mode=exc) is absorbed and the
loop re-enters with the queue intact, while ``mode=sigkill`` kills the
process honestly — the per-record-flushed JSONL keeps everything up to
the kill.

``--fleet N`` serves through a ``ServingFleet`` instead: N replica worker
processes (each its own JAX runtime + session on the requested layout,
ladder warmed before it takes traffic) behind the router
(docs/serving.md "Fleet"). Every per-engine flag applies PER REPLICA
(``--faults`` / ``SHALLOWSPEED_FAULTS`` inject into every worker — a
``die@dispatch=N:mode=sigkill`` plan kills replicas honestly and
exercises failover); ``--verify`` moves the bitwise-parity check into
each worker, per response. Without ``--checkpoint`` the replicas
initialize identically (deterministic seeded init), so fleet responses
stay replica-independent either way. Workers write per-replica
``<metrics-out>.r{replica_id}`` JSONL shards beside the parent's file.

Graceful drain: SIGTERM/SIGINT stop ADMISSION (no further requests are
submitted), drain everything already queued to a terminal verdict, flush
the metrics sink, and exit under the normal code contract — a preempted
server loses nothing it accepted.

With ``--metrics-out`` every request also leaves a schema-v10 span chain
(``trace`` records: queue/pack/dispatch/verify/ack — and, in fleet mode,
the cross-process fleet.queue/route/failover spans plus the per-replica
clock-offset handshake records in the parent file): render the Tracing
section with ``python -m shallowspeed_tpu.observability.report
<metrics-out>*`` to see per-phase latency attribution and the worst-k
request waterfalls (docs/observability.md § Tracing).

The stream also carries the live telemetry (schema v11): tumbling
``rollup`` windows and SLO ``alert`` transitions from the engine — or,
in fleet mode, from the parent AND each replica's ``.r*`` shard. Tail a
running server with ``python -m shallowspeed_tpu.observability.watch
<metrics-out> --follow``, or render a finished run with ``--once``.
``--knee-rps`` arms the knee-proximity alert rule with the measured
saturation knee from a ``bench_serving`` sweep record (the rule stays
off without it — measured evidence only, docs/observability.md § Live
telemetry & alerting).

Exit codes (aligned with train.py's documented contract):
  0  clean — including a signal-drained run whose accepted requests all
     served;
  1  failed responses: dropped / expired / error / unhealthy verdicts, or
     a bitwise mismatch under --verify (or an audit mismatch raising out
     of warm-up);
  2  usage errors (argparse);
  3  DEGRADED at exit — the health breaker is still open; in fleet mode,
     the fleet is still degraded (a QUORUM of replicas down) at exit
     (train.py's 3 is the health-monitor halt; this is its serving
     mirror).
"""

import argparse
import signal
import sys

import numpy as np


class GracefulStop:
    """The SIGTERM/SIGINT latch: ``install()`` registers both handlers
    (remembering the previous ones for ``restore()`` — the entry point is
    also invoked in-process by tests), the drivers poll ``stop()``."""

    def __init__(self):
        self.signum = None
        self._previous = {}

    def _handle(self, signum, frame):
        self.signum = signum

    def stop(self):
        return self.signum is not None

    def install(self):
        for s in (signal.SIGTERM, signal.SIGINT):
            self._previous[s] = signal.signal(s, self._handle)
        return self

    def restore(self):
        for s, h in self._previous.items():
            signal.signal(s, h)
        self._previous.clear()


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m shallowspeed_tpu.serving",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--pp", type=int, default=1)
    ap.add_argument(
        "--tp",
        type=int,
        default=1,
        help="tensor (model-axis) parallelism: serve through Megatron-"
        "sharded layers (forward-only — one all-reduce per row-parallel "
        "layer; --audit verifies the per-layer-pair tp all-reduces and "
        "still forbids every gradient collective)",
    )
    ap.add_argument(
        "--schedule",
        choices=["naive", "gpipe", "pipedream", "interleaved"],
        default="gpipe",
    )
    ap.add_argument("--virtual-stages", type=int, default=1)
    ap.add_argument("--global-batch-size", type=int, default=128)
    ap.add_argument("--mubatches", type=int, default=4)
    ap.add_argument("--data-dir", default=None)
    ap.add_argument(
        "--checkpoint",
        default=None,
        help="weights to serve (any layout's checkpoint restores onto the "
        "serving layout)",
    )
    ap.add_argument("--requests", type=int, default=200)
    ap.add_argument("--rate", type=float, default=100.0, help="offered rps")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--rows", default="1,2,3,4,8", help="request row-count choices"
    )
    ap.add_argument("--slo-ms", type=float, default=None)
    ap.add_argument(
        "--knee-rps",
        type=float,
        default=None,
        help="measured saturation knee (bench_serving sweep record's "
        "knee_rps) — arms the knee-proximity alert rule; absent = rule off",
    )
    ap.add_argument(
        "--deadline-ms",
        type=float,
        default=None,
        help="per-request deadline tag (default: score against --slo-ms); "
        "expired deadlines are SHED with verdict 'expired' at pack time",
    )
    ap.add_argument(
        "--closed-loop",
        type=int,
        default=0,
        metavar="C",
        help="drive a fixed population of C in-flight requests instead of "
        "open-loop Poisson arrivals",
    )
    ap.add_argument(
        "--max-slots",
        type=int,
        default=None,
        help="packing capacity per dispatch (default: the ladder's top rung)",
    )
    ap.add_argument(
        "--slot-rows",
        type=int,
        default=None,
        help="global rows per microbatch slot (default: 8, rounded up to a "
        "dp multiple)",
    )
    ap.add_argument(
        "--slot-ladder",
        default=None,
        help="comma-separated slot counts per dispatch (default 1,2,4,8,16) "
        "— bounds compiled inference programs at one per rung",
    )
    ap.add_argument(
        "--faults",
        default=None,
        help="chaos injection spec (e.g. 'error@dispatch=4,slow@dispatch=6"
        ":ms=50'); default: the SHALLOWSPEED_FAULTS environment plan",
    )
    ap.add_argument(
        "--retry-budget",
        type=int,
        default=2,
        help="total dispatch attempts per request before verdict 'error' "
        "(the shared retry.RetryPolicy budget)",
    )
    ap.add_argument(
        "--breaker",
        type=int,
        default=3,
        help="consecutive failed dispatches that open the health breaker "
        "(degraded: admission refused; exit 3 if still open at exit)",
    )
    ap.add_argument(
        "--fleet",
        type=int,
        default=0,
        metavar="N",
        help="serve through a ServingFleet of N replica worker processes "
        "(each its own JAX runtime on this layout) instead of one "
        "in-process engine; exit 3 if a quorum of replicas is down at "
        "exit",
    )
    ap.add_argument(
        "--fleet-policy",
        choices=["least_queue", "p2c"],
        default="least_queue",
        help="fleet placement policy: least outstanding load, or "
        "power-of-two-choices",
    )
    ap.add_argument(
        "--fleet-retry",
        type=int,
        default=2,
        help="fleet-level placement budget per request (the shared "
        "retry.RetryPolicy, one attempt per routing) — failover and "
        "verdict reroutes consume it",
    )
    ap.add_argument(
        "--fleet-max-queue",
        type=int,
        default=None,
        help="bounded fleet queue: admissions beyond it are DROPPED "
        "(reason fleet_queue_full); default unbounded",
    )
    ap.add_argument(
        "--aot-cache",
        default=None,
        metavar="DIR",
        help="AOT executable cache: the rung ladder warm-up deserializes "
        "compiled inference programs from this directory instead of "
        "recompiling (cold start in milliseconds; entries are written on "
        "the first cold compile, re-verified by the audit census before "
        "serving, and fall back to a clean recompile on corruption). In "
        "fleet mode every replica shares the directory — a scale-up "
        "replacement warms from what the first replicas compiled",
    )
    ap.add_argument(
        "--verify",
        action="store_true",
        help="re-compute every 'ok' response with a direct predict() of the "
        "same rows and demand bitwise equality (exit 1 on any mismatch)",
    )
    ap.add_argument(
        "--audit",
        action="store_true",
        help="census every compiled inference program against the "
        "forward-only serving contract before the first dispatch",
    )
    ap.add_argument("--metrics-out", default=None)
    args = ap.parse_args(argv)

    if args.fleet:
        return _fleet_main(args)

    from shallowspeed_tpu.api import TrainingSession
    from shallowspeed_tpu.observability import JsonlMetrics
    from shallowspeed_tpu.serving.engine import ServingEngine
    from shallowspeed_tpu.serving.loadgen import (
        poisson_arrivals,
        request_payloads,
        run_closed_loop,
        run_open_loop,
    )

    metrics = JsonlMetrics(args.metrics_out) if args.metrics_out else None
    session = TrainingSession(
        dp=args.dp,
        pp=args.pp,
        tp=args.tp,
        schedule=args.schedule,
        virtual_stages=args.virtual_stages,
        global_batch_size=args.global_batch_size,
        mubatches=args.mubatches,
        data_dir=args.data_dir,
        resume=args.checkpoint,
        metrics=metrics,
        audit=args.audit,
        aot_cache_dir=args.aot_cache,
        predict_slot_rows=args.slot_rows,
        predict_slot_ladder=(
            tuple(int(r) for r in args.slot_ladder.split(","))
            if args.slot_ladder
            else None
        ),
    )
    engine = ServingEngine(
        session,
        max_slots=args.max_slots,
        slo_ms=args.slo_ms,
        metrics=metrics,
        retry=args.retry_budget,
        breaker_threshold=args.breaker,
        faults=args.faults,
        knee_rps=args.knee_rps,
    )
    payloads = request_payloads(
        args.requests,
        session.spec.sizes[0],
        seed=args.seed,
        rows_choices=tuple(int(r) for r in args.rows.split(",") if r.strip()),
    )
    print(
        f"serving: DP={args.dp} x PP={args.pp} ({args.schedule}), "
        f"slot_rows={session.slot_rows}, ladder={session.slot_ladder}, "
        f"{args.requests} requests"
        + (
            f" closed-loop C={args.closed_loop}"
            if args.closed_loop
            else f" @ {args.rate} rps Poisson (seed {args.seed})"
        )
        + (f", weights from {args.checkpoint}" if args.checkpoint else "")
    )
    # warm every ladder rung before traffic: the measured percentiles must
    # be serving latency, not XLA compile time (and under --audit this is
    # also where every inference program's census gets verified)
    engine.warm_ladder()
    stopper = GracefulStop().install()
    try:
        if args.closed_loop:
            done = run_closed_loop(
                engine, payloads, concurrency=args.closed_loop,
                deadline_ms=args.deadline_ms, should_stop=stopper.stop,
            )
        else:
            arrivals = poisson_arrivals(args.rate, args.requests, seed=args.seed)
            done = run_open_loop(
                engine, payloads, arrivals, deadline_ms=args.deadline_ms,
                should_stop=stopper.stop,
            )
    finally:
        stopper.restore()
    rec = engine.record_summary(
        offered_rps=None if args.closed_loop else args.rate
    )
    if stopper.stop():
        sig = signal.Signals(stopper.signum).name
        print(
            f"{sig} received: admission stopped, queue drained "
            f"({rec['completed']} served of {len(done)} accepted)"
        )

    def ms(v):
        return f"{v * 1e3:.2f} ms" if v is not None else "n/a"

    print(
        f"completed {rec['completed']}/{args.requests}, dropped "
        f"{rec['dropped']}, expired {rec['expired']}, errors "
        f"{rec['errors']}, unhealthy {rec['unhealthy']}, "
        f"{rec['dispatches']} dispatches "
        f"({rec['slots_dispatched']} slots"
        + (
            f", padding waste {rec['padding_waste'] * 100:.1f}%)"
            if rec["padding_waste"] is not None
            else ")"
        )
    )
    print(
        f"latency p50 {ms(rec['p50_latency_s'])}, p99 "
        f"{ms(rec['p99_latency_s'])}, model floor "
        f"{ms(rec['latency_bound_s'])} ({rec['latency_bound_source']})"
    )
    if rec["goodput_rps"] is not None:
        print(
            f"goodput {rec['goodput_rps']:.1f} rps ({rec['slo_met']}/"
            f"{rec['completed']} within SLO), queue depth max "
            f"{rec['queue_depth_max']}"
        )
    if rec["breaker_trips"] or rec["reloads"]:
        print(
            f"degradation: {rec['breaker_trips']} breaker trip(s), "
            f"{rec['reloads']} reload(s)"
            + (
                f", recovered in {rec['recovery_s'] * 1e3:.1f} ms"
                if rec["recovery_s"] is not None
                else ""
            )
        )
    failures = (
        rec["dropped"] + rec["expired"] + rec["errors"] + rec["unhealthy"]
    )
    if args.verify:
        served = [r for r in done if r.verdict == "ok"]
        mismatched = 0
        for req in sorted(served, key=lambda r: r.id):
            direct = session.predict(payloads[req.id])  # ids are submit order
            if not np.array_equal(req.result, direct):
                mismatched += 1
        print(
            f"verify: {len(served) - mismatched}/{len(served)} responses "
            "bitwise-equal to direct predict()"
            + ("" if mismatched == 0 else f" — {mismatched} MISMATCHED")
        )
        failures += mismatched
    if metrics is not None:
        metrics.close()
        print(
            f"telemetry written: {metrics.path} (request + trace records; "
            "the report CLI renders the Serving and Tracing sections)"
        )
    if engine.degraded:
        print("serving: engine DEGRADED at exit (breaker open)", file=sys.stderr)
        return 3
    if failures:
        print(
            f"serving: {failures} dropped/expired/errored/unhealthy/"
            "incorrect response(s)",
            file=sys.stderr,
        )
        return 1
    return 0


def _fleet_main(args):
    """The ``--fleet N`` serve path: N replica workers behind the router,
    the same seeded load, the same exit-code contract (module
    docstring)."""
    from shallowspeed_tpu.observability import JsonlMetrics
    from shallowspeed_tpu.serving.fleet import ServingFleet
    from shallowspeed_tpu.serving.loadgen import (
        payload_in_dim,
        poisson_arrivals,
        request_payloads,
        run_closed_loop,
        run_open_loop,
    )

    metrics = JsonlMetrics(args.metrics_out) if args.metrics_out else None
    worker_config = {
        "session": dict(
            dp=args.dp,
            pp=args.pp,
            tp=args.tp,
            schedule=args.schedule,
            virtual_stages=args.virtual_stages,
            global_batch_size=args.global_batch_size,
            mubatches=args.mubatches,
            data_dir=args.data_dir,
            resume=args.checkpoint,
            audit=args.audit,
            aot_cache_dir=args.aot_cache,
            predict_slot_rows=args.slot_rows,
            predict_slot_ladder=(
                tuple(int(r) for r in args.slot_ladder.split(","))
                if args.slot_ladder
                else None
            ),
        ),
        "engine": dict(
            max_slots=args.max_slots,
            slo_ms=args.slo_ms,
            retry=args.retry_budget,
            breaker_threshold=args.breaker,
            faults=args.faults,
            knee_rps=args.knee_rps,
        ),
        "verify": args.verify,
    }
    fleet = ServingFleet(
        worker_config,
        n_replicas=args.fleet,
        policy=args.fleet_policy,
        max_queue=args.fleet_max_queue,
        slo_ms=args.slo_ms,
        retry=args.fleet_retry,
        metrics=metrics,
        seed=args.seed,
        knee_rps=args.knee_rps,
    )
    print(
        f"fleet: {args.fleet} replicas x (DP={args.dp} x PP={args.pp} x "
        f"TP={args.tp}, {args.schedule}), policy {args.fleet_policy}, "
        f"{args.requests} requests"
        + (
            f" closed-loop C={args.closed_loop}"
            if args.closed_loop
            else f" @ {args.rate} rps Poisson (seed {args.seed})"
        )
        + (f", weights from {args.checkpoint}" if args.checkpoint else "")
    )
    stopper = GracefulStop().install()
    try:
        fleet.start()  # every replica's ladder warmed before traffic
        payloads = request_payloads(
            args.requests,
            payload_in_dim(args.data_dir),
            seed=args.seed,
            rows_choices=tuple(
                int(r) for r in args.rows.split(",") if r.strip()
            ),
        )
        if args.closed_loop:
            done = run_closed_loop(
                fleet, payloads, concurrency=args.closed_loop,
                deadline_ms=args.deadline_ms, should_stop=stopper.stop,
            )
        else:
            arrivals = poisson_arrivals(args.rate, args.requests, seed=args.seed)
            done = run_open_loop(
                fleet, payloads, arrivals, deadline_ms=args.deadline_ms,
                should_stop=stopper.stop,
            )
        rec = fleet.record_summary(
            offered_rps=None if args.closed_loop else args.rate
        )
    finally:
        stopper.restore()
        fleet.stop()
    if stopper.stop():
        sig = signal.Signals(stopper.signum).name
        print(
            f"{sig} received: admission stopped, fleet drained "
            f"({rec['completed']} served)"
        )

    def ms(v):
        return f"{v * 1e3:.2f} ms" if v is not None else "n/a"

    print(
        f"completed {rec['completed']}/{args.requests}, dropped "
        f"{rec['dropped']}, expired {rec['expired']}, errors "
        f"{rec['errors']}, unhealthy {rec['unhealthy']}; latency p50 "
        f"{ms(rec['p50_latency_s'])}, p99 {ms(rec['p99_latency_s'])}"
    )
    routing = ", ".join(
        f"r{rid}: {n}" for rid, n in sorted(rec["routing"].items())
    )
    print(
        f"routing: {routing}"
        + (
            f" — skew {rec['routing_skew']:.2f}x"
            if rec["routing_skew"] is not None
            else ""
        )
    )
    if rec["failovers"] or rec["replicas_dead"]:
        print(
            f"failover: {rec['replicas_dead']} replica death(s), "
            f"{rec['failovers']} failover(s), {rec['failover_requeued']} "
            f"in-flight re-queued, {rec['reroutes']} reroute(s)"
            + (
                f", recovered in {rec['recovery_s'] * 1e3:.1f} ms"
                if rec["recovery_s"] is not None
                else ""
            )
        )
    if args.verify:
        served = rec["completed"]
        mism = rec["parity_mismatches"]
        print(
            f"verify: {served - mism}/{served} responses bitwise-equal to "
            "the serving replica's direct predict()"
            + ("" if mism == 0 else f" — {mism} MISMATCHED")
        )
    if metrics is not None:
        metrics.close()
        print(
            f"telemetry written: {metrics.path} (+ .r* replica shards; "
            "pass the glob to the report CLI for the merged Fleet and "
            "Tracing sections)"
        )
    failures = (
        rec["dropped"] + rec["expired"] + rec["errors"] + rec["unhealthy"]
        + rec["parity_mismatches"]
    )
    if rec["degraded"]:
        print(
            "serving: fleet DEGRADED at exit (quorum of replicas down)",
            file=sys.stderr,
        )
        return 3
    if failures:
        print(
            f"serving: {failures} dropped/expired/errored/unhealthy/"
            "incorrect response(s)",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
