"""BLE001 good twin: justified, narrowed, and re-raising broad excepts."""


def load_justified(path):
    try:
        return open(path).read()
    except Exception:  # noqa: BLE001 — probe is best-effort; absence is the signal
        return None


def load_narrow(path):
    try:
        return open(path).read()
    except (OSError, UnicodeDecodeError):
        return None


def load_reraise(path):
    try:
        return open(path).read()
    except Exception:
        raise ValueError(f"unreadable: {path}")
