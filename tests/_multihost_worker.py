"""Worker process for the REAL multi-host test (spawned by test_multihost.py).

Two of these run concurrently, each contributing 2 emulated CPU devices to a
4-device global runtime via ``jax.distributed`` — the JAX-native analogue of
the reference's ``mpirun -n N`` launch (reference train.py:87-94). Together
they exercise the full multihost surface:

  1. ``multihost.initialize`` against a localhost coordinator;
  2. ``multihost.shard_batch_for_process`` building a global batch from
     per-process shards;
  3. a cross-process ``psum`` over the ``dp`` axis (the DP gradient
     all-reduce path);
  4. one REAL pipeline-executor training step (DP=2 x PP=2, GPipe) over the
     process-spanning mesh, with ``dp`` laid across the process boundary the
     way it would be laid across hosts on a pod;
  5. the same step under ZeRO-1 + gradient clipping: the reduce_scatter that
     shards the gradient and the all_gather that rebuilds the params both
     cross the process boundary;
  6. the same with interleaved virtual stages (P=2 x V=2): ring relays stay
     on-process while the dp reduce crosses the boundary;
  7. the fused multi-epoch program (make_pipeline_run): two epochs in one
     dispatch with the cross-process dp psum inside the epochs-outer scan;
  8. the same step on the PALLAS kernel backend (flag-operand kernels,
     interpret mode on these CPU workers): the per-slot kernel units
     compose with jax.distributed and match the xla backend's loss.

Prints one JSON line {"pid", "psum_ok", "loss", "loss_z", "loss_i",
"loss_run", "loss_pallas"} on success; any assertion failure exits non-zero
and fails the parent test.
"""

import json
import os
import sys


def main():
    pid, port = int(sys.argv[1]), int(sys.argv[2])
    # CPU-only: keep the single-client TPU tunnel plugin out (see conftest.py)
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = [
        f
        for f in os.environ.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f
    ]
    os.environ["XLA_FLAGS"] = " ".join(
        flags + ["--xla_force_host_platform_device_count=2"]
    )

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    import jax

    jax.config.update("jax_platforms", "cpu")

    from shallowspeed_tpu.parallel import multihost

    # must run BEFORE any backend-initializing call
    multihost.initialize(
        coordinator_address=f"localhost:{port}", num_processes=2, process_id=pid
    )

    import numpy as np
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from shallowspeed_tpu.parallel.compat import shard_map

    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import schedules as S
    from shallowspeed_tpu.optimizer import SGD
    from shallowspeed_tpu.parallel import executor as E
    from shallowspeed_tpu.parallel import lower_schedule, make_mesh

    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.local_devices()) == 2
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    assert len(devs) == 4
    # dp rows == processes: stage relays (every tick) stay process-local,
    # the once-per-batch dp psum crosses the process boundary — the layout
    # multihost.py prescribes for real pods (pp on ICI, dp outer)
    mesh = make_mesh(2, 2, devices=devs)

    # --- cross-process DP psum over a process-locally-fed global array -----
    local = np.full((1, 4), float(pid + 1), np.float32)
    arr = multihost.shard_batch_for_process(local, mesh, P("dp"))
    summed = jax.jit(
        shard_map(
            lambda x: lax.psum(x, "dp"), mesh=mesh, in_specs=P("dp"), out_specs=P()
        )
    )(arr)
    np.testing.assert_array_equal(np.asarray(summed), np.full((1, 4), 3.0))

    # --- one real pipeline training step over the process-spanning mesh ----
    SIZES, B, M = (12, 10, 9, 8), 16, 2
    spec = Mo.make_model_spec(SIZES, 2, B)
    prog = lower_schedule(S.GPipeSchedule, M, 2)
    stacked, fl = E.stack_params(Mo.init_model(spec), spec)

    def put_global(x, pspec):
        sh = NamedSharding(mesh, pspec)
        return jax.make_array_from_callback(x.shape, sh, lambda idx: x[idx])

    def init_global(spec_, order=None):
        st, flg = E.stack_params(Mo.init_model(spec_), spec_, order=order)
        st = jax.tree.map(lambda x: put_global(x, P("pp")), st)
        flg = jax.tree.map(lambda x: put_global(x, P("pp")), flg)
        return st, flg

    stacked = jax.tree.map(lambda x: put_global(x, P("pp")), stacked)
    fl = jax.tree.map(lambda x: put_global(x, P("pp")), fl)

    rng = np.random.RandomState(0)
    X = rng.randn(B, SIZES[0]).astype(np.float32)
    Y = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], B)]
    half = B // 2
    xg = multihost.shard_batch_for_process(X[pid * half : (pid + 1) * half], mesh, P("dp"))
    yg = multihost.shard_batch_for_process(Y[pid * half : (pid + 1) * half], mesh, P("dp"))

    step = E.make_pipeline_step(mesh, spec, prog, half // M, SGD(0.05))
    _, _, loss = step(stacked, fl, (), xg, yg)

    # --- ZeRO-1 across the process boundary --------------------------------
    # dp spans the two processes, so the reduce_scatter that shards the
    # gradient and the all_gather that rebuilds the params BOTH cross it.
    from shallowspeed_tpu.optimizer import MomentumSGD

    opt_z = MomentumSGD(0.05, 0.9)
    st_z, fl_z = init_global(spec)
    oz = E.zero1_init_state(opt_z, spec, mesh)
    step_z = E.make_pipeline_step(
        mesh, spec, prog, half // M, opt_z, zero1=True, clip_norm=1.0
    )
    _, oz, loss_z = step_z(st_z, fl_z, oz, xg, yg)

    # --- interleaved virtual stages under the distributed runtime ---------
    # P=2 x V=2 = 4 model stages on each process's pp pair (ring relays incl.
    # the chunk wrap stay on-process) while the dp gradient reduce crosses
    # the process boundary — the recommended pod layout, in miniature.
    SIZES_I = (12, 11, 10, 9, 9, 8, 8, 8)  # len % (P*V=4) == 0, head owns a Linear
    spec_i = Mo.make_model_spec(SIZES_I, 4, B)
    order = E.interleave_order(4, 2)
    prog_i = lower_schedule(S.InterleavedSchedule, M, 2, virtual=2)
    st_i, fl_i = init_global(spec_i, order=order)
    step_i = E.make_pipeline_step(mesh, spec_i, prog_i, half // M, SGD(0.05))
    _, _, loss_i = step_i(st_i, fl_i, (), xg, yg)

    # --- fused multi-epoch run across the process boundary -----------------
    # the epochs-outer scan (make_pipeline_run) compiled once, executing two
    # epochs with the dp psum crossing processes inside a single dispatch
    st_r, fl_r = init_global(spec)
    run = E.make_pipeline_run(mesh, spec, prog, half // M, SGD(0.05))
    _, _, losses_r = run(st_r, fl_r, (), xg[None], yg[None], 2)
    losses_r = np.asarray(losses_r)
    assert losses_r.shape == (2,) and losses_r[1] < losses_r[0]

    # --- pallas kernel backend under the distributed runtime ---------------
    # identical init to the first GPipe step, so the flag kernels' loss must
    # match the xla backend's across the process-spanning mesh
    st_p, fl_p = init_global(spec)
    step_p = E.make_pipeline_step(
        mesh, spec, prog, half // M, SGD(0.05), kernel_backend="pallas"
    )
    _, _, loss_p = step_p(st_p, fl_p, (), xg, yg)
    np.testing.assert_allclose(float(loss_p), float(loss), rtol=1e-6)

    print(
        json.dumps(
            {
                "pid": pid,
                "psum_ok": True,
                "loss": float(loss),
                "loss_z": float(loss_z),
                "loss_i": float(loss_i),
                "loss_run": float(losses_r[-1]),
                "loss_pallas": float(loss_p),
            }
        )
    )


if __name__ == "__main__":
    main()
