"""Benchmark: MNIST-MLP training samples/sec/chip vs the NumPy reference.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "samples/s", "vs_baseline": N}

Protocol (BASELINE.md: the reference publishes no numbers, so the baseline is
measured here): train the flagship 7-layer MLP (sizes [784,128,...,10],
GLOBAL_BATCH=128, 4 microbatches, SGD lr=0.006) on MNIST-sized data and
report end-to-end training throughput.

- baseline: an independent NumPy implementation of the identical training
  step (microbatch grad accumulation, global-batch loss scaling) timed on
  this host's CPU — the reference's compute engine (NumPy+BLAS) doing the
  reference's exact work.
- value: this framework's jitted whole-epoch lax.scan on the default JAX
  device (the TPU chip when run by the driver).
- vs_baseline: value / baseline  (>1 = faster than the NumPy reference).

Timing protocol: two-point slope with forced host readbacks (see
slope_epoch_seconds) — required because on the remote-TPU tunnel dispatch is
fully async and jax.block_until_ready can return before execution finishes,
which would otherwise measure dispatch latency and report physically
impossible throughput.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np


def _ensure_responsive_backend(probe_timeout_s=180):
    """Never hang the benchmark on a wedged accelerator tunnel.

    Backend init for a remote-tunneled TPU can block indefinitely if the
    chip's claim is held by a dead client. When the tunnel plugin is active
    (PALLAS_AXON_POOL_IPS — the only configuration where the hang exists),
    probe device init in a subprocess; on timeout or init failure, fall back
    to the CPU platform. Returns a reason tag ('' = healthy) so the caller
    can label the published metric honestly and distinguish a hung tunnel
    from a backend that failed fast.

    stdout goes to DEVNULL and stderr to a temp FILE (never a pipe): a tunnel
    helper grandchild surviving the timeout kill would keep a captured pipe
    open and make the probe itself hang in communicate(), while a file lets
    us still report the backend's last error line.
    """
    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return ""  # no tunnel plugin, nothing to guard (and nothing to pay)
    # stderr goes to a FILE, not a pipe: a tunnel-helper grandchild surviving
    # the timeout kill would hold a pipe open and hang the probe itself
    import tempfile

    with tempfile.TemporaryFile() as errf:
        try:
            subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=probe_timeout_s,
                check=True,
                stdout=subprocess.DEVNULL,
                stderr=errf,
            )
            return ""
        except subprocess.TimeoutExpired:
            detail = f"unresponsive (> {probe_timeout_s}s to init)"
            tag = "_CPU_FALLBACK_TUNNEL_UNRESPONSIVE"
        except subprocess.CalledProcessError:
            # e.g. "UNAVAILABLE: TPU backend setup/compile error" — the real
            # run would die the same way; a degraded CPU number beats none
            errf.seek(0)
            tail = errf.read().decode(errors="replace").strip().splitlines()
            detail = f"failed to initialize ({tail[-1] if tail else 'no stderr'})"
            tag = "_CPU_FALLBACK_BACKEND_INIT_FAILED"
    print(f"bench: accelerator backend {detail}; falling back to CPU", file=sys.stderr)
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    return tag

from shallowspeed_tpu.api import (  # the reference's canonical config
    FLAGSHIP_BATCH as B,
    FLAGSHIP_LR as LR,
    FLAGSHIP_MUBATCHES as M,
    FLAGSHIP_SIZES as SIZES,
)
N_SAMPLES = 59392  # MNIST train size after drop-last to 128-multiples


def flops_per_sample():
    """~FLOPs per training sample: fwd 2P + bwd 4P for P = sum(in*out)."""
    return 6 * sum(SIZES[i] * SIZES[i + 1] for i in range(len(SIZES) - 1))


def sync_readback(tree):
    """Force device completion by reading back the smallest leaf.

    On the axon remote-TPU tunnel, dispatch is fully asynchronous AND
    jax.block_until_ready can return before execution finishes (observed:
    5 dispatched epochs "ready" in 0.35 ms, then a 7 s readback). A host
    readback cannot lie — materializing an output's bytes requires the whole
    dependency chain to have executed — so every timing boundary here ends
    in one.
    """
    import jax

    leaves = jax.tree.leaves(tree)
    np.asarray(min(leaves, key=lambda a: a.nbytes))


def slope_epoch_seconds(run_k, k1=2, k2=8, trials=3):
    """Honest seconds-per-epoch via a two-point slope.

    ``run_k(k)`` must dispatch k epochs (advancing its own state) and end
    with a forced readback (sync_readback). Timing k1 and k2 epochs and
    taking (t2-t1)/(k2-k1) cancels both the constant dispatch cost and the
    constant readback/tunnel-RTT cost, leaving pure per-epoch device time —
    robust even when block_until_ready is untrustworthy (see sync_readback).

    The chip pool shows transient multi-tenant contention (observed 3.3 ms
    to 131 ms per epoch for identical work across claim windows), so each
    leg is measured `trials` times and the MINIMUM PER LEG is taken BEFORE
    differencing: each leg's minimum converges to its least-contended cost
    and the constants still cancel. (Taking min over per-trial slopes
    instead would be biased fast whenever a trial's k1 leg was contended
    while its k2 leg was not.)
    """
    t_smalls, t_larges = [], []
    for _ in range(trials):
        t0 = time.perf_counter()
        run_k(k1)
        t_smalls.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        run_k(k2)
        t_larges.append(time.perf_counter() - t0)
    slope = (min(t_larges) - min(t_smalls)) / (k2 - k1)
    if slope <= 0:
        raise RuntimeError(
            "slope timing failed: k2 epochs never measurably slower than k1 "
            "(device not actually executing the work?)"
        )
    return slope


def measured_epoch_sps(epoch_fn, params, opt_state, X, Y, trials=3):
    """Honest samples/sec for a compiled-or-compilable whole-epoch function.

    Shared timing-protocol entry point (bench.py, scripts/bench_tpu_matrix.py
    and scripts/tpu_capture.py all measure through here so the protocol is
    defined once). ``epoch_fn(params, opt_state, X, Y) -> (params, opt_state,
    loss)`` with donated params/opt_state; X is (num_batches, M, mb, D).
    """
    state = {"p": params, "s": opt_state}

    def run_k(k):
        p, s = state["p"], state["s"]
        for _ in range(k):
            p, s, _ = epoch_fn(p, s, X, Y)
        state["p"], state["s"] = p, s
        sync_readback(p)

    run_k(1)  # compile + warmup, synced
    samples_per_epoch = X.shape[0] * X.shape[1] * X.shape[2]
    return samples_per_epoch / slope_epoch_seconds(run_k, trials=trials)


def numpy_baseline_sps(n_batches=40):
    """Fresh NumPy training step (reference-equivalent math), timed."""
    from shallowspeed_tpu.init import linear_init

    params = [linear_init(SIZES[i], SIZES[i + 1]) for i in range(len(SIZES) - 1)]
    rng = np.random.RandomState(0)
    xb = rng.randn(M, B // M, SIZES[0]).astype(np.float32)
    yb = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], (M, B // M))]

    def train_batch(params):
        acc = [(np.zeros_like(w), np.zeros_like(b)) for w, b in params]
        n = len(params)
        for x, t in zip(xb, yb):
            caches = []
            for i, (w, b) in enumerate(params):
                z = x @ w.T + b
                if i < n - 1:
                    caches.append((x, z > 0))
                    x = np.maximum(z, 0.0)
                else:
                    caches.append((x, None))
                    x = z
            ze = np.exp(x - np.max(x))
            p = ze / (ze.sum(axis=1, keepdims=True) + 1e-7)
            g = -2.0 * (t - p) / B
            gz = p * g
            g = gz - p * gz.sum(axis=1, keepdims=True)
            for i in reversed(range(n)):
                xi, mask = caches[i]
                if mask is not None:
                    g = g * mask
                acc[i] = (acc[i][0] + g.T @ xi, acc[i][1] + g.sum(0, keepdims=True))
                g = g @ params[i][0]
        return [
            (w - LR * gw, b - LR * gb) for (w, b), (gw, gb) in zip(params, acc)
        ]

    params = train_batch(params)  # warm BLAS
    t0 = time.perf_counter()
    for _ in range(n_batches):
        params = train_batch(params)
    dt = time.perf_counter() - t0
    return n_batches * B / dt


def jax_sps():
    import jax
    import jax.numpy as jnp

    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import trainer
    from shallowspeed_tpu.optimizer import SGD

    spec = Mo.make_model_spec(SIZES, 1, B)
    params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
    # fuse_mubatches: identical training (sum-gradient ledger), one full-batch
    # forward/backward per step — the TPU-shaped way to run the sequential
    # path. unroll: batch-scan unroll factor (bit-identical numerics); the
    # default can be overridden with the value scripts/tpu_capture.py measures
    # best on the chip.
    unroll = int(os.environ.get("SHALLOWSPEED_BENCH_UNROLL", "1"))
    epoch = trainer.make_train_epoch(
        spec, SGD(LR), fuse_mubatches=True, unroll=unroll
    )

    nb = N_SAMPLES // B
    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.rand(nb, M, B // M, SIZES[0]).astype(np.float32))
    Y = jnp.asarray(
        np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], (nb, M, B // M))]
    )

    return measured_epoch_sps(epoch, params, (), X, Y, trials=5)


def main():
    fallback_tag = _ensure_responsive_backend()
    baseline = numpy_baseline_sps()
    value = jax_sps()
    # a degraded run is unmistakable in the recorded metric itself
    metric = "mnist_mlp_train_samples_per_sec_per_chip" + fallback_tag
    # physical plausibility guard: if the implied FLOP rate exceeds anything a
    # single chip can do, the timing protocol was defeated — label, don't lie
    if value * flops_per_sample() > 100e12:
        metric += "_SUSPECT_TIMING"
        print(
            f"bench: implied {value * flops_per_sample() / 1e12:.0f} TFLOP/s "
            "exceeds single-chip fp32 plausibility; tagging metric",
            file=sys.stderr,
        )
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(value, 1),
                "unit": "samples/s",
                "vs_baseline": round(value / baseline, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
