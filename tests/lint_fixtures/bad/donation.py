"""SSP004 bad twin: donation outside the whitelisted modules."""


def make_step(jax, step_impl):
    return jax.jit(step_impl, donate_argnums=(0,))  # MARK
