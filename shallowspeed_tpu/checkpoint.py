"""Checkpoint / resume: layout-independent on-disk snapshots.

The reference has NO checkpointing in its framework (SURVEY §5.4 — only its
PyTorch baseline script saves weights for divergence comparison). Here it is
a first-class subsystem, designed around the same principle as init and
hashing: checkpoints store the *logical* per-layer (W, b) blocks in global
layer order, so a model trained DP=2 x PP=4 can be saved and resumed
sequentially, or vice versa — the layout is a property of the run, not of
the checkpoint.

Format: a single .npz (atomic rename on save) with arrays ``w{i}``/``b{i}``
per global layer, optional optimizer-state arrays ``ow{i}``/``ob{i}`` in the
same logical order (for stateful optimizers, e.g. momentum velocity), plus a
JSON metadata blob (sizes, global batch size, epoch, optimizer config).
"""

import json
import os
import tempfile
from pathlib import Path

import numpy as np

from shallowspeed_tpu.model import ModelSpec, make_model_spec

FORMAT_VERSION = 1


def _flatten_logical(params_list):
    """Per-stage ragged params -> flat global layer list (host numpy)."""
    import jax

    out = []
    for stage in params_list:
        for layer in stage:
            out.append(
                (
                    np.asarray(jax.device_get(layer["W"]), np.float32),
                    np.asarray(jax.device_get(layer["b"]), np.float32).reshape(1, -1),
                )
            )
    return out


def _opt_prefix(key):
    """Array-name prefix for an optimizer-state part. The unnamed part
    (momentum's whole-state mirror) keeps the original ``ow{i}``/``ob{i}``
    names, so round-1 checkpoints load unchanged; named parts (Adam's m/v)
    get ``o_{key}_w{i}``."""
    return ("ow", "ob") if key == "" else (f"o_{key}_w", f"o_{key}_b")


def save_checkpoint(
    path, params_list, spec: ModelSpec, epoch: int, extra=None, opt_state=None
):
    """Atomically write params (+ metadata) to ``path`` (.npz).

    ``opt_state``: optional logical optimizer state, as
    ``{"parts": {key: ragged_list}, "scalars": {key: float}}`` where each
    ragged_list has the SAME structure as ``params_list`` (state parts
    mirror the params — momentum velocity, Adam moments) — stored in the
    same logical layer order, so it is exactly as layout-independent as the
    weights; scalars (Adam's step count) go into the metadata blob.
    """
    path = Path(path)
    flat = _flatten_logical(params_list)
    if len(flat) != len(spec.sizes) - 1:
        raise ValueError(
            f"param count {len(flat)} does not match spec sizes {spec.sizes}"
        )
    parts = (opt_state or {}).get("parts", {})
    scalars = (opt_state or {}).get("scalars", {})
    meta = {
        "format_version": FORMAT_VERSION,
        "sizes": list(spec.sizes),
        "global_batch_size": spec.global_batch_size,
        "epoch": int(epoch),
        "has_opt_state": "" in parts,  # legacy momentum flag (round-1 readers)
        "opt_parts": sorted(parts),
        "opt_scalars": {k: float(v) for k, v in scalars.items()},
        "extra": extra or {},
    }
    arrays = {"meta": np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8)}
    for i, (w, b) in enumerate(flat):
        arrays[f"w{i}"] = w
        arrays[f"b{i}"] = b
    for key, ragged in parts.items():
        pw, pb = _opt_prefix(key)
        flat_opt = _flatten_logical(ragged)
        if len(flat_opt) != len(flat):
            raise ValueError(
                f"optimizer-state part {key!r} layer count {len(flat_opt)} != "
                f"param count {len(flat)}"
            )
        for i, (ow, ob) in enumerate(flat_opt):
            if ow.shape != flat[i][0].shape or ob.shape != flat[i][1].shape:
                raise ValueError(
                    f"optimizer-state part {key!r} layer {i} shape "
                    f"{ow.shape}/{ob.shape} does not mirror the params "
                    f"{flat[i][0].shape}/{flat[i][1].shape}"
                )
            arrays[f"{pw}{i}"] = ow
            arrays[f"{pb}{i}"] = ob
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=path.parent, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _partition(flat, spec: ModelSpec):
    """Flat global layer list -> per-stage ragged list for ``spec``."""
    out, k = [], 0
    for sspec in spec.stages:
        layers = []
        for _ in range(sspec.n_linears):
            w, b = flat[k]
            layers.append({"W": w, "b": b})
            k += 1
        out.append(layers)
    return out


def load_checkpoint(path, n_stages: int, global_batch_size=None, with_opt_state=False):
    """Load a checkpoint and re-partition it for an ``n_stages`` layout.

    ``global_batch_size``: the CURRENT run's global batch size — it feeds the
    loss-scaling spec, so resurrecting the saved value when the run uses a
    different batch size would silently mis-scale every gradient. Defaults to
    the saved value for same-configuration resumes.

    Returns (params_list, spec, meta): params_list is per-stage ragged host
    numpy ready for ``jax.tree.map(jnp.asarray, ...)`` (sequential) or
    ``executor.stack_params`` (pipeline). With ``with_opt_state=True``,
    returns (params_list, spec, meta, opt_state) where opt_state is
    ``{"parts": {key: ragged_list}, "scalars": {key: float}}`` (each part
    mirrors params_list), or None when the checkpoint stored none.
    """
    with np.load(Path(path)) as z:
        meta = json.loads(bytes(z["meta"]).decode())
        if meta.get("format_version") != FORMAT_VERSION:
            raise ValueError(f"unsupported checkpoint version: {meta}")
        n_layers = len(meta["sizes"]) - 1
        flat = [(z[f"w{i}"], z[f"b{i}"]) for i in range(n_layers)]
        # opt_parts supersedes has_opt_state; round-1 files have only the
        # latter (and only the unnamed part)
        part_keys = meta.get("opt_parts")
        if part_keys is None:
            part_keys = [""] if meta.get("has_opt_state") else []
        flat_parts = {}
        for key in part_keys:
            pw, pb = _opt_prefix(key)
            flat_parts[key] = [(z[f"{pw}{i}"], z[f"{pb}{i}"]) for i in range(n_layers)]
    if global_batch_size is None:
        global_batch_size = meta["global_batch_size"]
    spec = make_model_spec(meta["sizes"], n_stages, global_batch_size)
    params_list = _partition(flat, spec)
    # shape sanity against the re-partitioned spec
    for sspec, layers in zip(spec.stages, params_list):
        for l, layer in enumerate(layers):
            want = (sspec.local_sizes[l + 1], sspec.local_sizes[l])
            if layer["W"].shape != want:
                raise ValueError(
                    f"checkpoint layer shape {layer['W'].shape} != spec {want}"
                )
    if not with_opt_state:
        return params_list, spec, meta
    opt_state = None
    if flat_parts or meta.get("opt_scalars"):
        opt_state = {
            "parts": {k: _partition(v, spec) for k, v in flat_parts.items()},
            "scalars": dict(meta.get("opt_scalars", {})),
        }
    return params_list, spec, meta, opt_state
