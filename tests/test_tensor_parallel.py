"""Tensor (model-axis) parallelism: the Megatron-sharded layer path.

Contract under test (docs/performance.md "Tensor parallelism",
docs/lowering.md "Per-axis comms"):

- tp=1 never builds a tp axis and never traces the tp stage functions —
  the historical 2-axis programs are untouched (anchor leg);
- tp>1 layouts train to the sequential oracle's weights under the repo's
  standard CROSS-LAYOUT float tolerance: the row-parallel forward and
  column-parallel backward psums split a contraction across ranks, which
  reassociates the fp sum exactly like a different dp width reassociates
  the gradient all-reduce (docs/numerics.md). Same-layout A/B knobs at a
  FIXED tp — bucketed vs anchor gradient sync, split vs combined
  backward — stay BITWISE, and those legs are asserted with array_equal;
- the compiled program's collective census carries the per-axis contract:
  the tp axis demands >= (fwd sites + bwd sites) all-reduce ops
  (executor.tp_allreduce_sites), the dp payload shrinks by tp, and the
  forward-only serving contract still forbids every gradient collective.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shallowspeed_tpu import model as Mo
from shallowspeed_tpu import schedules as S
from shallowspeed_tpu import trainer
from shallowspeed_tpu.observability import program_audit
from shallowspeed_tpu.optimizer import SGD, MomentumSGD
from shallowspeed_tpu.parallel import executor as E
from shallowspeed_tpu.parallel import gradsync, lower_schedule, make_mesh
from shallowspeed_tpu.parallel.mesh import make_mesh_with_layout, mesh_tp

SIZES = (40, 36, 32, 28, 24, 20, 14, 10)  # 7 Linears; pp in {1, 2} below
M, B = 4, 32


# ---------------------------------------------------------------------------
# mesh + static geometry
# ---------------------------------------------------------------------------


def test_mesh_tp_axis_and_layout_note():
    mesh, layout = make_mesh_with_layout(2, 2, tp=2)
    assert mesh.axis_names == ("dp", "pp", "tp")
    assert dict(mesh.shape) == {"dp": 2, "pp": 2, "tp": 2}
    assert layout in ("topology-aware", "order-preserving")
    assert mesh_tp(mesh) == 2


def test_mesh_tp1_keeps_the_historical_two_axes():
    mesh = make_mesh(2, 2)
    assert mesh.axis_names == ("dp", "pp")
    assert mesh_tp(mesh) == 1
    assert mesh_tp(make_mesh(2, 2, tp=1)) == 1
    with pytest.raises(ValueError, match="need 16 devices"):
        make_mesh(2, 2, tp=4)
    with pytest.raises(ValueError, match="tp must be >= 1"):
        make_mesh(1, 1, tp=0)


def test_slot_shapes_tp_rounds_to_multiples():
    spec = Mo.make_model_spec(SIZES, 2, B)
    base = E.slot_shapes(spec)
    assert E.slot_shapes(spec, 1) == base  # tp=1 identical (the anchor)
    for tp in (2, 4):
        dims = E.slot_shapes(spec, tp)
        assert all(o % tp == 0 and i % tp == 0 for o, i in dims)
        # rounding only ever pads upward
        assert all(o >= bo and i >= bi for (o, i), (bo, bi) in zip(dims, base))


def test_tp_local_dims_parity_and_sites():
    spec = Mo.make_model_spec(SIZES, 2, B)
    dims = E.slot_shapes(spec, 2)
    w_dims, b_widths, xs_w, mask_w = E.tp_local_dims(dims, 2)
    for l, (o, i) in enumerate(dims):
        if l % 2 == 0:  # column-parallel: W row band, sharded mask
            assert w_dims[l] == (o // 2, i)
            assert xs_w[l] == i and mask_w[l] == o // 2
        else:  # row-parallel: W column band, sharded input
            assert w_dims[l] == (o, i // 2)
            assert xs_w[l] == i // 2 and mask_w[l] == o
        assert b_widths[l] == o // 2
    fwd, bwd = E.tp_allreduce_sites(spec, 2, training=True)
    L = len(dims)
    assert len(fwd) == L // 2 + (L % 2)  # odd slots + closing gather
    assert len(bwd) == (L + 1) // 2  # even slots
    fwd_inf, bwd_inf = E.tp_allreduce_sites(spec, 2, training=False)
    assert fwd_inf == fwd and bwd_inf == []


# ---------------------------------------------------------------------------
# training equivalence
# ---------------------------------------------------------------------------


def _data(seed=7):
    rng = np.random.RandomState(seed)
    X = rng.randn(2, B, SIZES[0]).astype(np.float32)
    Y = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], (2, B))]
    return X, Y


def _train_mesh(
    dp, pp, tp, sched=S.GPipeSchedule, zero1=False, gbb=0, bsplit=False,
    clip=0.05, opt=None,
):
    spec = Mo.make_model_spec(SIZES, pp, B)
    mesh = make_mesh(dp, pp, tp=tp)
    prog = lower_schedule(sched, M, pp, backward_split=bsplit)
    stacked, flags = E.init_stacked(spec, mesh)
    opt = opt or SGD(0.01)
    ost = E.zero1_init_state(opt, spec, mesh) if zero1 else opt.init(stacked)
    step = E.make_pipeline_step(
        mesh, spec, prog, B // dp // M, opt, zero1=zero1, clip_norm=clip,
        with_grad_norm=True, grad_bucket_bytes=gbb,
    )
    X, Y = _data()
    for i in range(2):
        stacked, ost, loss, gn = step(
            stacked, flags, ost, jnp.asarray(X[i]), jnp.asarray(Y[i])
        )
    got = [l for s in E.unstack_params(stacked, spec) for l in s]
    return got, float(loss), float(gn)


def _train_sequential(clip=0.05, opt=None):
    spec1 = Mo.make_model_spec(SIZES, 1, B)
    params = jax.tree.map(jnp.asarray, Mo.init_model(spec1))
    opt = opt or SGD(0.01)
    step1 = trainer.make_train_step(spec1, opt, clip_norm=clip)
    st = opt.init(params)
    X, Y = _data()
    for i in range(2):
        params, st = step1(
            params, st,
            jnp.asarray(X[i].reshape(M, B // M, -1)),
            jnp.asarray(Y[i].reshape(M, B // M, -1)),
        )
    return [l for stage in params for l in stage]


TP_LAYOUTS = {
    # layout -> (dp, pp, tp, kwargs) — the dp x pp x tp lattice corners,
    # clip active throughout (the norm reduction must span ('pp','tp'))
    "tp2": (1, 1, 2, {}),
    "tp4": (1, 1, 4, {}),
    "dp2-tp2": (2, 1, 2, {}),
    "pp2-tp2": (1, 2, 2, {}),
    "dp2-pp2-tp2": (2, 2, 2, dict(sched=S.PipeDreamFlushSchedule)),
    "zero1-tp2": (2, 2, 2, dict(zero1=True, opt=MomentumSGD(0.005, 0.9))),
}


@pytest.mark.parametrize("layout", sorted(TP_LAYOUTS))
def test_tp_matches_sequential(layout):
    """The TP acceptance criterion: every dp x pp x tp lattice corner —
    including the 8-device dp2 x pp2 x tp2 cube and ZeRO-1 over it —
    trains to the sequential oracle's weights/loss/grad-norm under the
    repo's cross-layout tolerance, with global-norm clipping active (the
    clip factor reads the ('pp','tp')-spanning reduction, so a
    double-counted or dropped shard would shift every weight)."""
    dp, pp, tp, kw = TP_LAYOUTS[layout]
    opt = kw.get("opt")
    want = _train_sequential(opt=opt)
    got, loss, gn = _train_mesh(dp, pp, tp, **kw)
    assert np.isfinite(loss) and np.isfinite(gn), layout
    for a, b in zip(want, got):
        np.testing.assert_allclose(
            np.asarray(a["W"]), b["W"], rtol=5e-4, atol=5e-6, err_msg=layout
        )
        np.testing.assert_allclose(
            np.asarray(a["b"]).reshape(-1), b["b"].reshape(-1),
            rtol=5e-4, atol=5e-6, err_msg=layout,
        )


def test_tp_bucketed_sync_bitwise_identical_to_anchor():
    """The bit-identity contract where it GENUINELY holds at tp > 1:
    bucketed vs anchor gradient sync on the same tp2 layout — weights,
    loss AND the pre-clip grad norm are array_equal (the dp collectives
    sum the same per-shard elements either way)."""
    base_w, base_loss, base_gn = _train_mesh(2, 1, 2)
    for gbb in (512, 8192):
        w, loss, gn = _train_mesh(2, 1, 2, gbb=gbb)
        assert loss == base_loss and gn == base_gn, gbb
        for a, b in zip(base_w, w):
            np.testing.assert_array_equal(a["W"], b["W"], err_msg=str(gbb))
            np.testing.assert_array_equal(a["b"], b["b"], err_msg=str(gbb))


def test_tp_backward_split_bitwise_identical_to_unsplit():
    """Split-backward at tp2: the tp dgrad chain and deferred wgrads are
    the same expressions at different ticks (the _tp stage functions are
    literal compositions), so pp2 x tp2 split == unsplit bit for bit."""
    base_w, base_loss, base_gn = _train_mesh(1, 2, 2)
    w, loss, gn = _train_mesh(1, 2, 2, bsplit=True)
    assert loss == base_loss and gn == base_gn
    for a, b in zip(base_w, w):
        np.testing.assert_array_equal(a["W"], b["W"])
        np.testing.assert_array_equal(a["b"], b["b"])


def test_tp_zero1_state_roundtrip():
    """The zero1 flat layout under tp: host logical state -> device rows ->
    host logical state is the identity (the (pp*tp, dp*chunk) row order
    matches P(('pp','tp'),'dp')), so tp checkpoints stay layout-free."""
    spec = Mo.make_model_spec(SIZES, 2, B)
    mesh = make_mesh(2, 2, tp=2)
    opt = MomentumSGD(0.005, 0.9)
    rng = np.random.RandomState(3)
    logical = {
        "parts": {
            "": [
                [
                    {
                        "W": rng.randn(*np.asarray(l["W"]).shape).astype(np.float32),
                        "b": rng.randn(*np.asarray(l["b"]).shape).astype(np.float32),
                    }
                    for l in stage
                ]
                for stage in Mo.init_model(spec)
            ]
        },
        "scalars": {},
    }
    state = E.zero1_state_from_logical(logical, opt, spec, mesh)
    back = E.zero1_state_to_logical(state, opt, spec, mesh)
    for stage_a, stage_b in zip(logical["parts"][""], back["parts"][""]):
        for a, b in zip(stage_a, stage_b):
            np.testing.assert_array_equal(a["W"], b["W"])
            np.testing.assert_array_equal(
                np.asarray(a["b"]).reshape(-1), np.asarray(b["b"]).reshape(-1)
            )


# ---------------------------------------------------------------------------
# census contract
# ---------------------------------------------------------------------------


def _compiled_census(dp, pp, tp, training=True, zero1=False):
    spec = Mo.make_model_spec(SIZES, pp, B)
    mesh = make_mesh(dp, pp, tp=tp)
    sched = S.GPipeSchedule if training else S.InferenceSchedule
    prog = lower_schedule(sched, M, pp, training=training)
    stacked, flags = E.init_stacked(spec, mesh)
    mb = B // dp // M
    if training:
        opt = SGD(0.01)
        ost = E.zero1_init_state(opt, spec, mesh) if zero1 else opt.init(stacked)
        step = E.make_pipeline_step(mesh, spec, prog, mb, opt, zero1=zero1)
        compiled = step.lower(
            stacked, flags, ost,
            jax.ShapeDtypeStruct((B, SIZES[0]), jnp.float32),
            jax.ShapeDtypeStruct((B, SIZES[-1]), jnp.float32),
        ).compile()
    else:
        step = E.make_pipeline_step(mesh, spec, prog, mb)
        compiled = step.lower(
            stacked, flags, jax.ShapeDtypeStruct((B, SIZES[0]), jnp.float32)
        ).compile()
    ops = program_audit.parse_collectives(compiled.as_text())
    expected = program_audit.expected_comms(
        spec, dp, pp, prog=prog, zero1=zero1, mubatch_size=mb, tp=tp
    )
    return ops, program_audit.census_of_ops(ops), expected


def test_tp_training_census_matches_contract():
    ops, census, expected = _compiled_census(2, 2, 2)
    assert "tp" in expected["axes"]
    tp_axis = expected["axes"]["tp"]
    assert tp_axis["hlo_min_all_reduce_ops"] == (
        tp_axis["sites_fwd"] + tp_axis["sites_bwd"]
    )
    # the compiled program really holds the Megatron psums (plus the dp
    # sync, loss and clip reductions — the floor is a lower bound)
    assert census["all_reduce"]["count"] >= tp_axis["hlo_min_all_reduce_ops"]
    program_audit.verify_census(census, expected, ops=ops)
    # dp payload shrinks: each device syncs only its Megatron shard
    spec = Mo.make_model_spec(SIZES, 2, B)
    dp_axis = expected["axes"]["dp"]
    assert dp_axis["grad_bytes_per_device"] < 4 * E.stacked_flat_len(spec, 2)
    dims2 = E.slot_shapes(spec, 2)
    assert E.stacked_flat_len(spec, 2, 2) == sum(
        o * i // 2 for o, i in dims2
    ) + sum(o // 2 for o, _ in dims2)


def test_tp_census_floor_catches_dropped_collectives():
    """A contract whose tp floor exceeds the compiled census must refuse:
    the enforcement leg is real, not decorative."""
    ops, census, expected = _compiled_census(1, 1, 2)
    tampered = dict(expected)
    tampered["axes"] = dict(expected["axes"])
    tampered["axes"]["tp"] = dict(expected["axes"]["tp"])
    tampered["axes"]["tp"]["hlo_min_all_reduce_ops"] = (
        census["all_reduce"]["count"] + 7
    )
    with pytest.raises(program_audit.AuditMismatchError, match="tensor-parallel"):
        program_audit.verify_census(census, tampered, ops=ops)
    # and the honest contract passes the same census
    program_audit.verify_census(census, expected, ops=ops)


def test_tp_inference_census_forward_only():
    """Serving under TP: the forward-only contract keeps the gradient
    collectives forbidden (reduce-scatter/all-gather would mean the
    training lowering leaked into the serving path) while requiring the
    per-layer-pair forward psums — and the compiled inference program at
    pp2 x tp2 satisfies it."""
    ops, census, expected = _compiled_census(1, 2, 2, training=False)
    assert expected["inference"] is True
    assert "reduce_scatter" in expected["forbidden"]
    assert "all_gather" in expected["forbidden"]
    assert expected["axes"]["tp"]["sites_bwd"] == 0
    program_audit.verify_census(census, expected, ops=ops)
    # a leaked gradient collective is refused — both kinds: the ZeRO
    # collectives by prohibition, and an EXTRA all-reduce (the anchor-mode
    # dp sync's shape) by the tp upper pin (at most sites + the preds psum)
    leaky = dict(census)
    leaky["reduce_scatter"] = {"count": 1, "bytes": 1024}
    with pytest.raises(program_audit.AuditMismatchError, match="reduce_scatter"):
        program_audit.verify_census(leaky, expected, ops=ops)
    need = expected["axes"]["tp"]["hlo_min_all_reduce_ops"]
    leaky_ar = dict(census)
    leaky_ar["all_reduce"] = {
        "count": need + 2,
        "bytes": census["all_reduce"]["bytes"] + 4096,
    }
    with pytest.raises(
        program_audit.AuditMismatchError, match="leaked into the serving path"
    ):
        program_audit.verify_census(leaky_ar, expected, ops=ops)


def test_tp_bucket_plan_sizes_are_local_shards():
    """The gradsync planners bucket THIS DEVICE's Megatron shards: total
    planned bytes at tp2 are exactly half the tp1 plan's, and the
    emitters' leaf shapes match the executor's local gradient shapes."""
    spec = Mo.make_model_spec(SIZES, 1, B)
    p1 = gradsync.plan_buckets(spec, 2, 1, 4096, tp=1)
    p2 = gradsync.plan_buckets(spec, 2, 1, 4096, tp=2)
    dims2 = E.slot_shapes(spec, 2)
    w_dims, b_widths, _, _ = E.tp_local_dims(dims2, 2)
    for group in p2.buckets:
        for leaf in group:
            if leaf.kind == "W":
                assert tuple(leaf.shape)[1:] == w_dims[leaf.slot]
            else:
                assert tuple(leaf.shape)[1] == b_widths[leaf.slot]
    total1 = p1.total_grad_bytes()
    total2 = p2.total_grad_bytes()
    # tp2 dims are rounded up before halving, so <= holds with equality
    # whenever no rounding occurred
    assert total2 <= total1
    assert total2 == 4 * E.stacked_flat_len(spec, 1, 2)


# ---------------------------------------------------------------------------
# session-level end to end
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tp_data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("tp_data")
    rng = np.random.RandomState(0)
    for suffix, n in (("train", 128), ("val", 64)):
        np.save(d / f"x_{suffix}.npy", rng.rand(n, SIZES[0]).astype(np.float32))
        np.save(
            d / f"y_{suffix}.npy",
            np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], n)],
        )
    return d


def test_tp_session_trains_audited_and_predicts(tp_data_dir):
    """TrainingSession(tp=2) end to end: strict-audit training (the census
    contract is enforced before the first dispatch), prediction through
    the ladder rung programs bitwise-stable, and eval equal to the
    sequential reference's predictions under the same weights."""
    from shallowspeed_tpu.api import TrainingSession

    common = dict(
        sizes=SIZES, global_batch_size=32, mubatches=2, lr=0.01,
        data_dir=tp_data_dir,
    )
    run = TrainingSession(dp=2, tp=2, audit=True, **common)
    loss = run.train_epoch()
    assert np.isfinite(loss)
    seq = TrainingSession(**common)
    seq.train_epoch()
    # cross-layout tolerance (split contractions reassociate — the dp
    # precedent), asserted on the trained weights
    for a, b in zip(
        [l for s in seq.params() for l in s],
        [l for s in run.params() for l in s],
    ):
        np.testing.assert_allclose(
            np.asarray(a["W"]), np.asarray(b["W"]), rtol=5e-4, atol=5e-6
        )
    # predict: same rows through two different rung programs are bitwise
    x = np.asarray(np.random.RandomState(5).rand(3, SIZES[0]), np.float32)
    p_small = run.predict(x)
    p_large = run.predict(np.concatenate([x, x, x], axis=0))[:3]
    np.testing.assert_array_equal(p_small, p_large)
    assert run.accuracy() >= 0.0


def test_tp_session_validations():
    from shallowspeed_tpu.api import TrainingSession

    with pytest.raises(ValueError, match="tp must be >= 1"):
        TrainingSession(tp=0)
    with pytest.raises(ValueError, match="pallas"):
        TrainingSession(dp=2, tp=2, kernel_backend="pallas")
    with pytest.raises(ValueError, match="sequential path only"):
        TrainingSession(tp=2, fuse_mubatches=True)
